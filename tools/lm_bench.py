"""LM serving fast-path bench (ISSUE 4): TTFT, tokens/s, dispatches/token.

Measures the three fast-path features of ``veles_tpu.serving.LMEngine``
— radix prefix cache, chunked prefill, prompt-lookup speculative
decoding — each toggled against the same two workloads, and reports the
numbers docs/PERF.md records:

- ``shared_prefix``: 8 requests sharing a system-prompt prefix
  (``tools/load_gen.py::lm_prompts`` — the ONE prompt generator the
  serving load tests and this bench share), measuring prefilled-token
  count, prefix-cache hit tokens, and TTFT;
- ``repetitive``: structured/repetitive prompts (the prompt-lookup
  -friendly shape: templated text, code, logs), measuring decode
  dispatches per generated token and tokens/s.

Every leg ALSO asserts its outputs bit-identical to the direct greedy
``ops/transformer.py::generate`` — a fast path that changed tokens
would be a bug, not a speedup, so the bench refuses to report it.

Standalone (CPU is fine; the dispatches/token and hit-rate evidence is
platform-independent, wall-clock numbers scale with the platform)::

    python tools/lm_bench.py [--smoke] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from load_gen import lm_prompts  # noqa: E402


def build_params(vocab=32, d_model=64, n_heads=4, n_layers=2,
                 max_len=256, seed=7):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    prng.reset()
    prng.seed_all(seed)
    host = init_transformer_params(prng.get("init"), vocab,
                                   d_model=d_model, n_heads=n_heads,
                                   n_layers=n_layers, max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


def repetitive_prompts(n, vocab, length, seed=3):
    """Prompt-lookup-friendly prompts: a short random motif tiled to
    ``length`` (templated text / logs / code shape) — the n-gram draft
    finds the motif's continuation almost every step."""
    rng = numpy.random.RandomState(seed)
    out = []
    for _ in range(n):
        motif = rng.randint(0, vocab, rng.randint(4, 9))
        reps = length // len(motif) + 1
        out.append(numpy.tile(motif, reps)[:length].tolist())
    return out


def expected_rows(params, prompts, n_new, n_heads, max_len):
    import jax.numpy as jnp
    from veles_tpu.ops.transformer import generate
    return [numpy.asarray(generate(
        params, jnp.asarray([p], jnp.int32), n_new, n_heads,
        temperature=0.0, max_len=max_len))[0] for p in prompts]


def run_leg(params, n_heads, max_len, prompts, n_new, expect,
            slots=4, **engine_kw):
    """One engine config over one prompt list; returns the metrics
    record (parity asserted, not reported on faith).

    The workload runs TWICE: the COLD pass supplies the prefill /
    prefix-cache accounting (what a first arrival of this traffic
    costs — the 7/8-hit acceptance shape), then metrics are reset and
    the WARM pass supplies wall/TTFT/dispatch numbers — non-chunked
    engines compile prompt-bucket programs lazily, and timing a
    steady-state serving claim through one-off compiles would hand the
    chunked legs an unearned 10x."""
    from veles_tpu.serving import LMEngine, ServingMetrics
    engine = LMEngine(params, n_heads=n_heads, max_len=max_len,
                      slots=slots, queue_depth=max(64, len(prompts)),
                      metrics=ServingMetrics("lm_bench"),
                      **engine_kw).start()

    def one_pass():
        t0 = time.monotonic()
        futures = [engine.submit(p, n_new) for p in prompts]
        rows = [f.result(timeout=600) for f in futures]
        wall = time.monotonic() - t0
        for p, row, exp in zip(prompts, rows, expect):
            got = numpy.concatenate([p, row])
            if not numpy.array_equal(got, exp):
                raise AssertionError(
                    "fast-path output diverged from greedy generate "
                    "for prompt of length %d under %r"
                    % (len(p), engine_kw))
        return wall, engine.metrics.snapshot()

    try:
        _, cold = one_pass()
        engine.metrics = ServingMetrics("lm_bench_warm")
        wall, warm = one_pass()
        cc, c = cold["counters"], warm["counters"]
        tokens = c.get("tokens_out", 0)
        dispatches = c.get("decode_dispatches", 0)
        return {
            "features": {k: v for k, v in engine_kw.items() if v},
            "requests": len(prompts),
            "tokens_out": tokens,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(tokens / wall, 1) if wall else 0.0,
            "decode_dispatches": dispatches,
            "dispatches_per_token": (round(dispatches / tokens, 3)
                                     if tokens else None),
            # cold-pass facts: what FIRST arrivals of this traffic cost
            "prefill_tokens": cc.get("prefill_tokens", 0),
            "prefix_hit_tokens": cc.get("prefix_hit_tokens", 0),
            "draft_tokens": c.get("draft_tokens", 0),
            "draft_accepted": c.get("draft_accepted", 0),
            "draft_accept_rate": (
                round(c["draft_accepted"] / c["draft_tokens"], 3)
                if c.get("draft_tokens") else None),
            "ttft_mean_s": round(warm["ttft"]["mean"], 5),
            "parity_vs_generate": True,     # asserted above, both passes
        }
    finally:
        engine.stop()


def run_bench(smoke=False, slots=4, chunk=16, cache=256, spec_k=4,
              n_new=32, requests=8, vocab=32, max_len=256):
    if smoke:
        n_new, requests, max_len = 8, 4, 128
    params = build_params(vocab=vocab, max_len=max_len)
    n_heads = 4
    feature_sets = {
        "baseline": {},
        "chunked": {"prefill_chunk": chunk},
        "prefix_cache": {"prefix_cache": cache, "prefill_chunk": chunk},
        "spec": {"spec_k": spec_k},
        "all": {"prefix_cache": cache, "prefill_chunk": chunk,
                "spec_k": spec_k},
    }
    # workload A: shared system prompt (load_gen's generator — one
    # request per "client", every prompt shares the prefix)
    mean_len = min(64, max_len - n_new - spec_k - 1)
    grid = lm_prompts(requests, 1, vocab=vocab, mean_len=mean_len,
                      shared_frac=0.6,
                      max_len=max_len - n_new - spec_k - 1, seed=11)
    shared = [grid[(ci, 0)] for ci in range(requests)]
    # workload B: repetitive text (prompt-lookup's home turf)
    rep = repetitive_prompts(requests, vocab,
                             min(48, max_len - n_new - spec_k - 1))
    results = {"model": {"vocab": vocab, "d_model": 64, "n_layers": 2,
                         "max_len": max_len},
               "slots": slots, "n_new": n_new,
               "workloads": {}}
    # the single-lane repetitive workload ISOLATES speculation: with
    # one slot the baseline is exactly 1 dispatch/token, so any value
    # below 1 is the draft acceptance and nothing else (multi-slot
    # continuous batching is already sub-1 across lanes)
    for wname, prompts, wslots in (
            ("shared_prefix", shared, slots),
            ("repetitive", rep, slots),
            ("repetitive_single_lane", rep[:max(2, requests // 2)], 1)):
        expect = expected_rows(params, prompts, n_new, n_heads, max_len)
        legs = {}
        for fname, kw in feature_sets.items():
            legs[fname] = run_leg(params, n_heads, max_len, prompts,
                                  n_new, expect, slots=wslots, **kw)
            print("%s/%s: %s" % (wname, fname, json.dumps(legs[fname])),
                  file=sys.stderr)
        results["workloads"][wname] = legs
    # headline facts the acceptance criteria name
    lane1 = results["workloads"]["repetitive_single_lane"]
    sp_cache = results["workloads"]["shared_prefix"]["prefix_cache"]
    sp_base = results["workloads"]["shared_prefix"]["baseline"]
    results["headline"] = {
        "dispatches_per_token_plain_single_lane":
            lane1["baseline"]["dispatches_per_token"],
        "dispatches_per_token_speculative_single_lane":
            lane1["spec"]["dispatches_per_token"],
        "prefill_tokens_baseline": sp_base["prefill_tokens"],
        "prefill_tokens_prefix_cache": sp_cache["prefill_tokens"],
        "prefix_hit_tokens": sp_cache["prefix_hit_tokens"],
        "prefill_flops_saved_frac": round(
            1 - sp_cache["prefill_tokens"]
            / max(sp_base["prefill_tokens"], 1), 3),
    }
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes (CI validation)")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--chunk", type=int, default=16,
                        help="prefill chunk size for the chunked legs")
    parser.add_argument("--cache", type=int, default=256,
                        help="prefix cache capacity (chunks)")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="speculative draft length")
    parser.add_argument("--n-new", type=int, default=32)
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the record here")
    args = parser.parse_args(argv)
    results = run_bench(smoke=args.smoke, slots=args.slots,
                        chunk=args.chunk, cache=args.cache,
                        spec_k=args.spec_k, n_new=args.n_new,
                        requests=args.requests)
    line = json.dumps(results)
    print(line)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
