"""LM serving fast-path bench (ISSUE 4): TTFT, tokens/s, dispatches/token.

Measures the three fast-path features of ``veles_tpu.serving.LMEngine``
— radix prefix cache, chunked prefill, prompt-lookup speculative
decoding — each toggled against the same two workloads, and reports the
numbers docs/PERF.md records:

- ``shared_prefix``: 8 requests sharing a system-prompt prefix
  (``tools/load_gen.py::lm_prompts`` — the ONE prompt generator the
  serving load tests and this bench share), measuring prefilled-token
  count, prefix-cache hit tokens, and TTFT;
- ``repetitive``: structured/repetitive prompts (the prompt-lookup
  -friendly shape: templated text, code, logs), measuring decode
  dispatches per generated token and tokens/s.

The PAGED KV legs (ISSUE 6) toggle ``paged_kv`` against the same
workloads plus a ``mixed_length`` one, and report the memory facts:
KV bytes resident, row copies performed on prefix hits (ZERO on the
paged path — asserted, not just reported), pages served by reference,
and — the acceptance headline — the lane count achievable at FIXED KV
memory on a mixed-length prompt distribution vs the contiguous layout
(``fixed_kv_memory``: same bytes, ≥2× the lanes).

The SHARDED legs (ISSUE 8) run every workload tensor-parallel
(``tp2`` — one engine over a 2-device mesh), data-parallel
(``replicas2`` — 2 engines behind the metrics-driven router, with
per-replica routing counts, queue-depth spread and balance ratio) and
stacked (``tp2_replicas2`` — 4 devices), each streaming the same
bench-style summary line; every record carries ``devices`` and
``mfu_per_device`` so fleet utilization reads honestly.  On a
single-device host these legs bank ``skipped`` records; ``--devices
N`` forces an N-device CPU dryrun host (the MULTICHIP suite's
forced-host-device-count gear).

The MEGASTEP legs (ISSUE 13) run the fused K-tokens-per-dispatch
decode program on every workload — ``megastep`` (plain greedy, K=16)
and ``megastep_all`` (K=8 stacked with the prefix cache, chunked
prefill and in-graph speculation) — streaming the dispatches/token
column per leg and ASSERTING < 0.1 on the single-lane greedy legs
(vs the 0.547 best single-lane record the megastep replaces), plus
``megastep_waste_frac`` (lane-iterations run frozen past a lane's
early exit) so the K tradeoff is measured, not guessed.

Every leg ALSO asserts its outputs bit-identical to the direct greedy
``ops/transformer.py::generate`` — a fast path that changed tokens
would be a bug, not a speedup, so the bench refuses to report it.

A full summary JSON line (``summary_record`` — the same record shape
as ``bench.py``) streams to stdout after EVERY completed leg,
last-line-wins: a tunneled TPU run killed by the outer watchdog still
banks a parseable record (the BENCH_r04/r05 failure mode).

Standalone (CPU is fine; the dispatches/token and hit-rate evidence is
platform-independent, wall-clock numbers scale with the platform)::

    python tools/lm_bench.py [--smoke] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from load_gen import lm_prompts  # noqa: E402

# THE FLOPs/MFU model moved to veles_tpu/serving/timeseries.py
# (ISSUE 14): the live mfu_live gauge and the bench's per-leg MFU
# column must read the same numerator/denominator — re-exported here
# so every existing consumer keeps its import path
from veles_tpu.serving.timeseries import (  # noqa: E402,F401
    CPU_NOMINAL_FLOPS, TPU_PEAK_FLOPS, decode_flops_per_token,
    peak_flops_estimate)


def build_params(vocab=32, d_model=64, n_heads=4, n_layers=2,
                 max_len=256, seed=7):
    import jax
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.ops.transformer import init_transformer_params
    prng.reset()
    prng.seed_all(seed)
    host = init_transformer_params(prng.get("init"), vocab,
                                   d_model=d_model, n_heads=n_heads,
                                   n_layers=n_layers, max_len=max_len)
    return jax.tree.map(jnp.asarray, host)


def repetitive_prompts(n, vocab, length, seed=3):
    """Prompt-lookup-friendly prompts: a short random motif tiled to
    ``length`` (templated text / logs / code shape) — the n-gram draft
    finds the motif's continuation almost every step."""
    rng = numpy.random.RandomState(seed)
    out = []
    for _ in range(n):
        motif = rng.randint(0, vocab, rng.randint(4, 9))
        reps = length // len(motif) + 1
        out.append(numpy.tile(motif, reps)[:length].tolist())
    return out


def mixed_length_prompts(n, vocab, lo, hi, seed=13):
    """Lengths spread uniformly across [lo, hi] — the distribution
    where per-lane paging pays: a contiguous layout charges every one
    of these the worst case, a paged one only its own span."""
    rng = numpy.random.RandomState(seed)
    return [rng.randint(0, vocab, int(length)).tolist()
            for length in rng.randint(lo, hi + 1, n)]


def expected_rows(params, prompts, n_new, n_heads, max_len):
    import jax.numpy as jnp
    from veles_tpu.ops.transformer import generate
    return [numpy.asarray(generate(
        params, jnp.asarray([p], jnp.int32), n_new, n_heads,
        temperature=0.0, max_len=max_len))[0] for p in prompts]


def _emulate_device_latency(engines, seconds):
    """Wrap each engine's decode/verify/chunk dispatch with a
    block-until-ready + sleep — the DEVICE-BOUND serving regime on a
    CPU dryrun host.  On a real accelerator the engine worker thread
    idle-waits on the device per dispatch, which is exactly what
    data-parallel replicas overlap; on a shared-CPU dryrun box the
    'device' compute competes for the same cores, so raw replica legs
    measure core contention, not the router.  This emulation restores
    the regime the layer is FOR, and is always labeled
    (``emulated_step_latency_s``) in the records it touches."""
    import time as time_mod

    import jax

    def wrap(fn):
        def wrapped(*args):
            out = fn(*args)
            jax.block_until_ready(out)
            time_mod.sleep(seconds)
            return out
        return wrapped

    for engine in engines:
        for name in ("_step_jit", "_verify_jit", "_chunk_jit",
                     "_prefill_jit", "_megastep_jit",
                     "_whilestep_jit"):
            fn = getattr(engine, name, None)
            if fn is not None:
                setattr(engine, name, wrap(fn))


def run_leg(params, n_heads, max_len, prompts, n_new, expect,
            slots=4, flops_per_token=None, step_latency_s=0.0,
            **engine_kw):
    """One engine config over one prompt list; returns the metrics
    record (parity asserted, not reported on faith), including the
    MFU column (``flops_per_token`` × warm tokens/s over the
    platform's peak — ISSUE 7's the-gap-is-kernel-shaped metric) and,
    on ``attn_kernel`` legs, which attention path actually ran.

    SHARDED legs (ISSUE 8): ``tp=N`` runs the engine tensor-parallel
    over an N-device mesh; ``replicas=R`` builds R engines (each on
    its own device slice) behind the metrics-driven Router and the
    record gains per-replica routing/queue-depth facts plus
    ``mfu_per_device`` (MFU against the FLEET's peak — devices ×
    single-device peak).  A leg the host cannot seat (too few
    devices) returns a ``skipped`` record instead of crashing the
    bench: on CPU, ``--devices N`` forces an N-device dryrun host.

    The workload runs TWICE: the COLD pass supplies the prefill /
    prefix-cache accounting (what a first arrival of this traffic
    costs — the 7/8-hit acceptance shape), then metrics are reset and
    the WARM pass supplies wall/TTFT/dispatch numbers — non-chunked
    engines compile prompt-bucket programs lazily, and timing a
    steady-state serving claim through one-off compiles would hand the
    chunked legs an unearned 10x."""
    import jax
    from veles_tpu.serving import (LMEngine, Router, ServingMetrics,
                                   replica_device_slices)
    tp = int(engine_kw.pop("tp", 0) or 0)
    replicas = int(engine_kw.pop("replicas", 1) or 1)
    trace = bool(engine_kw.pop("trace", False))
    n_devices = max(1, replicas) * max(1, tp)
    features = {k: v for k, v in engine_kw.items() if v}
    if tp:
        features["tp"] = tp
    if replicas > 1:
        features["replicas"] = replicas
    tracer = None
    if trace:
        # the TRACED legs (ISSUE 12): one shared tracer across the
        # fleet, every request retained — after the run the span trees
        # must VERIFY (one root per request, no orphans, no unclosed
        # spans) or the leg fails; the ring is sized so closed-loop
        # admission retries cannot evict real requests
        from veles_tpu.serving import SpanTracer
        features["trace"] = True
        tracer = SpanTracer(mode="all",
                            last=8 * max(1, len(prompts)) + 64)
    if n_devices > 1 and jax.device_count() < n_devices:
        # recorded, never silent: a truncated matrix must say so
        return {"features": features,
                "skipped": "needs %d devices, have %d (CPU: rerun "
                           "with --devices %d under JAX_PLATFORMS="
                           "cpu)" % (n_devices, jax.device_count(),
                                     n_devices)}
    # the SAME replica→devices mapping serve_lm ships
    slices = (replica_device_slices(replicas, tp)
              if replicas > 1 else None)

    def build(idx=None, tag="lm_bench"):
        devices = None
        labels = None
        if idx is not None:
            devices = slices[idx]
            labels = {"replica": str(idx)}
        return LMEngine(params, n_heads=n_heads, max_len=max_len,
                        slots=slots, queue_depth=max(64, len(prompts)),
                        metrics=ServingMetrics(tag, labels=labels),
                        tp=tp, devices=devices, tracer=tracer,
                        name=tag if idx is None else "%s_r%d"
                        % (tag, idx), **engine_kw)

    if replicas > 1:
        engines = [build(i) for i in range(replicas)]
        server = Router(engines,
                        metrics=ServingMetrics("lm_bench_router"),
                        tracer=tracer)
    else:
        engines = [build()]
        server = engines[0]
    server.start()
    if step_latency_s:
        _emulate_device_latency(engines, step_latency_s)
        features["emulated_step_latency_s"] = step_latency_s

    def fresh_metrics(tag):
        for i, e in enumerate(engines):
            e.metrics = ServingMetrics(
                tag, labels={"replica": str(i)} if replicas > 1
                else None)
        if replicas > 1:
            server.metrics = ServingMetrics(tag + "_router")

    def combined_snapshot():
        """Aggregate the fleet: counters summed, histogram sums/counts
        summed (for the TTFT mean), peaks summed (aggregate
        concurrency), plus the raw per-replica snapshots."""
        snaps = [e.metrics.snapshot() for e in engines]
        counters = {}
        for s in snaps:
            for k, v in s["counters"].items():
                counters[k] = counters.get(k, 0) + v
        ttft_n = sum(s["ttft"]["count"] for s in snaps)
        return {
            "counters": counters,
            "ttft_mean": (sum(s["ttft"]["sum"] for s in snaps)
                          / ttft_n if ttft_n else 0.0),
            "slots_busy_peak": sum(
                int(s["gauges"].get("slots_busy_peak", 0))
                for s in snaps),
            "queue_depth_peaks": [
                int(s["gauges"].get("queue_depth_peak", 0))
                for s in snaps],
            "per_replica": snaps,
        }

    def submit_retrying(p):
        """Closed-loop admission: a 429 (queue or pool pressure) backs
        off per Retry-After and resubmits — large --requests against a
        small pool must measure throughput, not crash the leg (the
        single-lane paged pool admits ~3 requests' pages at a time)."""
        from veles_tpu.serving import Overloaded
        deadline = time.monotonic() + 600
        while True:
            try:
                return server.submit(p, n_new)
            except Overloaded as e:
                if time.monotonic() > deadline:
                    raise
                time.sleep(min(getattr(e, "retry_after", 0.05), 0.25))

    def one_pass():
        t0 = time.monotonic()
        futures = [submit_retrying(p) for p in prompts]
        rows = [f.result(timeout=600) for f in futures]
        wall = time.monotonic() - t0
        for p, row, exp in zip(prompts, rows, expect):
            got = numpy.concatenate([p, row])
            if not numpy.array_equal(got, exp):
                raise AssertionError(
                    "fast-path output diverged from greedy generate "
                    "for prompt of length %d under %r"
                    % (len(p), features))
        return wall, combined_snapshot()

    try:
        _, cold = one_pass()
        fresh_metrics("lm_bench_warm")
        wall, warm = one_pass()
        cc, c = cold["counters"], warm["counters"]
        tokens = c.get("tokens_out", 0)
        dispatches = c.get("decode_dispatches", 0)
        if features.get("attn_kernel"):
            from veles_tpu.ops.pallas_kernels import on_tpu
            if not on_tpu() and features["attn_kernel"] != "force" \
                    and not c.get("attn_kernel_fallbacks"):
                # the CPU acceptance criterion: the fallback path must
                # be EXERCISED and METERED, not silently absent
                raise AssertionError(
                    "attn_kernel leg on CPU did not increment the "
                    "fallback counter under %r" % (features,))
        if features.get("paged_kv"):
            # the paged layout has NO row-copy install path — a prefix
            # hit is a page reference; any copy counted here is a bug
            if cc.get("kv_row_copies", 0) or c.get("kv_row_copies", 0):
                raise AssertionError(
                    "paged leg performed %d KV row copies under %r — "
                    "prefix hits must be page references"
                    % (cc.get("kv_row_copies", 0)
                       + c.get("kv_row_copies", 0), features))
        megastep_cols = {}
        if features.get("megastep"):
            lane_iters = c.get("megastep_lane_iterations", 0)
            waste_frac = (
                round(c.get("megastep_wasted_iterations", 0)
                      / lane_iters, 4) if lane_iters else None)
            megastep_cols = {
                "megastep_dispatches": c.get("megastep_dispatches", 0),
                "megastep_tokens": c.get("megastep_tokens", 0),
                # tokens wasted to early-exit masking: the fraction of
                # lane-iterations the fused program ran frozen — the
                # measured cost side of the K tradeoff
                "megastep_waste_frac": waste_frac,
            }
            if features.get("refill_ring"):
                # ISSUE 19: in-graph re-arms from the standby ring —
                # each one is a dispatch boundary the loop skipped
                megastep_cols["megastep_refills"] = \
                    c.get("megastep_refills", 0)
            if slots == 1 and n_new >= 32 \
                    and int(features["megastep"]) >= 8:
                # THE acceptance criterion (ISSUE 13): single-lane
                # greedy at K >= 8 must measure < 0.1 dispatches per
                # token — asserted, not reported on faith
                dpt = (dispatches / tokens) if tokens else None
                if dpt is None or dpt >= 0.1:
                    raise AssertionError(
                        "megastep leg measured %s dispatches/token "
                        "(acceptance bound < 0.1) under %r"
                        % (dpt, features))
                if features.get("megastep_mode") == "while":
                    # ISSUE 19 acceptance: the while loop's early exit
                    # must RETIRE the scan waste tail (0.225 on the
                    # spec K=8 single-lane record) ...
                    if waste_frac is None or waste_frac >= 0.02:
                        raise AssertionError(
                            "whilestep leg measured waste_frac %s "
                            "(acceptance bound < 0.02, scan record "
                            "0.225) under %r" % (waste_frac, features))
                    # ... while holding dispatches/token at or under
                    # the K=16 scan megastep record (0.062 — itself
                    # the rounded record column, so compare rounded;
                    # the K=8 spec leg has a different dispatch
                    # geometry and answers only to the < 0.1 bound)
                    if int(features["megastep"]) >= 16 \
                            and round(dpt, 3) > 0.062:
                        raise AssertionError(
                            "whilestep leg measured %s dispatches/"
                            "token (acceptance bound <= 0.062, the "
                            "K=16 scan record) under %r"
                            % (dpt, features))
        tps = tokens / wall if wall else 0.0
        peak, peak_src = peak_flops_estimate()
        mfu = (tps * flops_per_token / peak
               if flops_per_token else None)
        record = {
            "features": features,
            "requests": len(prompts),
            "tokens_out": tokens,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(tps, 1),
            # the ISSUE 7 column: model FLOPs actually flowing over the
            # platform's advertised peak — the kernel-vs-XLA legs read
            # off against each other here.  ``mfu`` stays against ONE
            # device's peak (comparable across every leg);
            # ``mfu_per_device`` divides by the leg's device count —
            # the honest utilization of a sharded/replicated fleet
            "mfu": round(mfu, 6) if mfu is not None else None,
            "mfu_per_device": (round(mfu / n_devices, 6)
                               if mfu is not None else None),
            "devices": n_devices,
            "mfu_peak_source": peak_src,
            "attn_kernel_dispatches": c.get("attn_kernel_dispatches",
                                            0),
            "attn_kernel_fallbacks": c.get("attn_kernel_fallbacks", 0),
            "decode_dispatches": dispatches,
            "dispatches_per_token": (round(dispatches / tokens, 3)
                                     if tokens else None),
            # cold-pass facts: what FIRST arrivals of this traffic cost
            "prefill_tokens": cc.get("prefill_tokens", 0),
            "prefix_hit_tokens": cc.get("prefix_hit_tokens", 0),
            "draft_tokens": c.get("draft_tokens", 0),
            "draft_accepted": c.get("draft_accepted", 0),
            "draft_accept_rate": (
                round(c["draft_accepted"] / c["draft_tokens"], 3)
                if c.get("draft_tokens") else None),
            "ttft_mean_s": round(warm["ttft_mean"], 5),
            # paged-KV memory facts (contiguous legs report them too,
            # for the side-by-side): device KV footprint, row copies
            # paid installing prefix hits (cold pass — 0 when paged),
            # pages served by reference, copy-on-write count, and the
            # peak concurrent lanes the layout actually sustained
            "kv_bytes_resident": sum(e.kv_bytes_resident()
                                     for e in engines),
            "kv_row_copies": cc.get("kv_row_copies", 0),
            "kv_pages_referenced": cc.get("kv_pages_referenced", 0),
            "kv_cow_copies": (cc.get("kv_cow_copies", 0)
                              + c.get("kv_cow_copies", 0)),
            "slots_busy_peak": warm["slots_busy_peak"],
            "parity_vs_generate": True,     # asserted above, both passes
        }
        record.update(megastep_cols)
        if replicas > 1:
            # router evidence: server-side placement counts (includes
            # requeues), the queue-depth high-water spread across the
            # fleet, and per-replica warm tokens
            routed = server.routed_counts()
            record["replica_routed"] = routed
            record["replica_balance_ratio"] = (
                round(max(routed) / min(routed), 3)
                if min(routed) else None)
            record["replica_queue_depth_peak"] = \
                warm["queue_depth_peaks"]
            record["replica_queue_depth_spread"] = (
                max(warm["queue_depth_peaks"])
                - min(warm["queue_depth_peaks"]))
            record["replica_tokens_out"] = [
                s["counters"].get("tokens_out", 0)
                for s in warm["per_replica"]]
        if tracer is not None:
            # span-tree integrity is an ASSERTION, not a report: every
            # request rooted, no orphans, no unclosed spans — under
            # whatever fast-path combination this leg ran — and the
            # Chrome export must be strict-parseable JSON
            from veles_tpu.serving import cost_ledger, verify_integrity
            recs = tracer.requests()
            integrity = verify_integrity(recs)
            if integrity["requests"] < 2 * len(prompts):
                raise AssertionError(
                    "traced leg retained %d request traces for %d "
                    "requests x 2 passes under %r"
                    % (integrity["requests"], len(prompts), features))
            chrome = tracer.export_chrome()
            json.loads(json.dumps(chrome, allow_nan=False))
            ledger = cost_ledger(recs)
            if not ledger:
                raise AssertionError(
                    "traced leg produced an empty cost ledger "
                    "under %r" % (features,))
            record["trace"] = {
                "requests": integrity["requests"],
                "spans": integrity["spans"],
                "integrity": True,
                "chrome_events": len(chrome["traceEvents"]),
                "ledger_rows": len(ledger),
                "ledger_dispatches": int(sum(r["dispatches"]
                                             for r in ledger)),
            }
        return record
    finally:
        server.stop()


def fixed_kv_memory_comparison(params, n_heads, max_len, chunk, n_new,
                               vocab, budget_slots=4, requests=16):
    """ACCEPTANCE leg: the SAME mixed-length workload through (a) the
    contiguous layout sized to ``budget_slots`` worst-case lanes and
    (b) a paged pool of EXACTLY the same KV bytes
    (``budget_slots·max_len/chunk`` pages) — reporting the lane count
    each layout sustains.  The contiguous layout is structurally capped
    at ``budget_slots``; the paged pool turns the headroom between the
    mixed lengths and the worst case into extra concurrent lanes."""
    lo, hi = max(4, chunk // 2), max(chunk, (max_len - n_new) // 2)
    prompts = mixed_length_prompts(requests, vocab, lo, hi)
    expect = expected_rows(params, prompts, n_new, n_heads, max_len)
    fpt = decode_flops_per_token(
        vocab, params["embed"].shape[1], len(params["blocks"]),
        int(numpy.mean([len(p) for p in prompts])) + n_new // 2,
        n_heads=n_heads)
    contig = run_leg(params, n_heads, max_len, prompts, n_new, expect,
                     slots=budget_slots, flops_per_token=fpt)
    # -1: the reserved scratch page counts against the byte budget, so
    # both layouts hold EXACTLY budget_slots·max_len KV rows per block
    pool_pages = budget_slots * max_len // chunk - 1
    paged = run_leg(params, n_heads, max_len, prompts, n_new, expect,
                    slots=min(requests, pool_pages),
                    paged_kv=pool_pages, prefill_chunk=chunk,
                    flops_per_token=fpt)
    ratio = paged["slots_busy_peak"] / float(budget_slots)
    return {
        "budget_slots_contiguous": budget_slots,
        "kv_bytes_contiguous": contig["kv_bytes_resident"],
        "kv_bytes_paged": paged["kv_bytes_resident"],
        "pool_pages": pool_pages,
        "prompt_lengths": sorted(len(p) for p in prompts),
        "slots_peak_contiguous": contig["slots_busy_peak"],
        "slots_peak_paged": paged["slots_busy_peak"],
        "slots_ratio_vs_contiguous": round(ratio, 2),
        "contiguous": contig,
        "paged": paged,
    }


def replica_scaling_comparison(params, n_heads, max_len, chunk, n_new,
                               vocab, slots=4, requests=16,
                               step_latency_s=0.005):
    """ACCEPTANCE leg (ISSUE 8): the SAME mixed-length workload through
    (a) ONE paged engine and (b) 2 replicas behind the metrics router,
    both under the emulated device-bound regime
    (:func:`_emulate_device_latency` — per-dispatch idle wait, the
    regime real accelerators serve in and the one replica overlap
    exists for).  Reports the aggregate-throughput ratio and the
    router's balance evidence.  The RAW shared-core legs (tp2/
    replicas2 in the feature matrix) stay in the record for the honest
    side-by-side: on a dryrun box whose cores one engine already
    saturates, raw replication measures core contention, not the
    serving layer."""
    import jax
    if jax.device_count() < 2:
        # before the parity references: skipping must be free, not
        # cost `requests` full greedy generates first
        return {"skipped": "needs 2 devices, have %d"
                           % jax.device_count()}
    lo, hi = max(4, chunk // 2), max(chunk, (max_len - n_new) // 2)
    prompts = mixed_length_prompts(requests, vocab, lo, hi)
    expect = expected_rows(params, prompts, n_new, n_heads, max_len)
    fpt = decode_flops_per_token(
        vocab, params["embed"].shape[1], len(params["blocks"]),
        int(numpy.mean([len(p) for p in prompts])) + n_new // 2,
        n_heads=n_heads)
    single = run_leg(params, n_heads, max_len, prompts, n_new, expect,
                     slots=slots, paged_kv=True, prefill_chunk=chunk,
                     step_latency_s=step_latency_s,
                     flops_per_token=fpt)
    pair = run_leg(params, n_heads, max_len, prompts, n_new, expect,
                   slots=slots, replicas=2, paged_kv=True,
                   prefill_chunk=chunk, step_latency_s=step_latency_s,
                   flops_per_token=fpt)
    ratio = (pair["tokens_per_sec"]
             / max(single["tokens_per_sec"], 1e-9))
    return {
        "emulated_step_latency_s": step_latency_s,
        "tokens_per_sec_single": single["tokens_per_sec"],
        "tokens_per_sec_replicas2": pair["tokens_per_sec"],
        "replicas2_speedup": round(ratio, 2),
        "replica_routed": pair["replica_routed"],
        "replica_balance_ratio": pair["replica_balance_ratio"],
        "replica_queue_depth_spread":
            pair["replica_queue_depth_spread"],
        "single": single,
        "replicas2": pair,
    }


def run_lint_leg(results):
    """The dispatch-hygiene assertion leg (ISSUE 17): run every
    ``tools/veles_lint.py`` pass over the shipped tree BEFORE the
    serving legs — a hot path that regressed into an implicit host
    sync or a silently-compiled twin program would make every number
    below describe a slower engine than the one the repo ships, so
    the bench refuses to report on a dirty tree.  Streams the
    bench-schema ``lint_clean`` record (``check_stream_records.py
    --tool veles_lint`` validates the shape) and ASSERTS zero
    findings."""
    import veles_lint
    findings, _, stats = veles_lint.run_check()
    record = veles_lint.clean_record(findings, stats)[0]
    print(json.dumps(record), flush=True)
    assert not findings, (
        "lint_clean leg: %d finding(s) on the shipped tree — %s"
        % (len(findings), "; ".join(str(f) for f in findings[:5])))
    results["lint_clean"] = record["configs"]


def bench_max_len(smoke):
    """THE bench max_len — main()'s --chunk divisibility pre-check and
    run_bench() must read the same value, or the check validates a
    geometry the run doesn't use."""
    return 128 if smoke else 256


def run_bench(smoke=False, slots=4, chunk=16, cache=256, spec_k=4,
              n_new=32, requests=8, vocab=32, max_len=None):
    if max_len is None:
        max_len = bench_max_len(smoke)
    if smoke:
        n_new, requests = 8, 4
    params = build_params(vocab=vocab, max_len=max_len)
    n_heads = 4
    d_model = int(params["embed"].shape[1])
    n_layers = len(params["blocks"])
    feature_sets = {
        "baseline": {},
        "chunked": {"prefill_chunk": chunk},
        "prefix_cache": {"prefix_cache": cache, "prefill_chunk": chunk},
        "spec": {"spec_k": spec_k},
        "all": {"prefix_cache": cache, "prefill_chunk": chunk,
                "spec_k": spec_k},
        # ISSUE 6: the paged KV pool, alone and under the full fast
        # path — same workloads, so the row-copy and footprint columns
        # read off directly against the contiguous legs above
        "paged": {"paged_kv": True, "prefill_chunk": chunk},
        "paged_all": {"paged_kv": True, "prefix_cache": cache,
                      "prefill_chunk": chunk, "spec_k": spec_k},
        # ISSUE 7: the Pallas serving kernels against the same
        # workloads — the kernel-vs-XLA MFU comparison reads off the
        # 'paged' legs above.  On CPU these run the automatic XLA
        # fallback END TO END (no crash, attn_kernel_fallbacks
        # increments — asserted by run_leg); the kernel MFU numbers
        # are a TPU-session fact.
        "paged_kernel": {"paged_kv": True, "prefill_chunk": chunk,
                         "attn_kernel": "auto"},
        "paged_kernel_all": {"paged_kv": True, "prefix_cache": cache,
                             "prefill_chunk": chunk, "spec_k": spec_k,
                             "attn_kernel": "auto"},
        # ISSUE 8: sharded serving on the same workloads — tensor-
        # parallel decode (tp2, 2-device mesh), data-parallel replicas
        # behind the metrics router (replicas2, aggregate throughput +
        # balance evidence), and both stacked (tp2_replicas2, 4
        # devices).  Hosts without the devices bank a 'skipped' record
        # per leg (CPU dryrun: --devices N).
        "tp2": {"tp": 2, "paged_kv": True, "prefill_chunk": chunk},
        "replicas2": {"replicas": 2, "paged_kv": True,
                      "prefill_chunk": chunk},
        "tp2_replicas2": {"tp": 2, "replicas": 2, "paged_kv": True,
                          "prefill_chunk": chunk},
        # ISSUE 13: the fused decode megastep — K decode iterations
        # per device dispatch (lax.scan; spec_k folds its propose/
        # verify in-graph on the megastep_all leg).  The single-lane
        # greedy acceptance criterion rides run_leg: < 0.1
        # dispatches/token (vs 0.547 best single-lane before), plus
        # the megastep_waste_frac column so the K tradeoff (early-exit
        # masking wastes tail iterations) is measured, not guessed.
        "megastep": {"megastep": 16, "paged_kv": True,
                     "prefill_chunk": chunk},
        "megastep_all": {"megastep": 8, "paged_kv": True,
                         "prefix_cache": cache, "prefill_chunk": chunk,
                         "spec_k": spec_k},
        # ISSUE 19: the persistent while-loop megastep — same K caps
        # as the scan legs above, but the loop EXITS at the realized
        # iteration count; whilestep_all stacks the standby refill
        # ring + cache + chunk + spec on the K=8 cap.  run_leg asserts
        # the acceptance pair on the single-lane legs: waste_frac
        # < 0.02 (vs the 0.225 scan K=8 spec record) and
        # dispatches/token <= 0.062 (the K=16 scan record).
        "whilestep": {"megastep": 16, "megastep_mode": "while",
                      "paged_kv": True, "prefill_chunk": chunk},
        "whilestep_all": {"megastep": 8, "megastep_mode": "while",
                          "paged_kv": True, "prefix_cache": cache,
                          "prefill_chunk": chunk, "spec_k": spec_k,
                          "refill_ring": 2},
        # ISSUE 12: the TRACED legs — the full fast-path stack with the
        # span tracer armed.  Parity still asserted (tracing must not
        # perturb output), span-tree integrity asserted per request,
        # and the record carries the cost-ledger shape (rows, deduped
        # dispatch count).  traced_tp2_all is the acceptance combo
        # (prefix_cache + prefill_chunk + spec_k + paged_kv + tp
        # dryrun); hosts without 2 devices bank a 'skipped' record.
        "traced_all": {"paged_kv": True, "prefix_cache": cache,
                       "prefill_chunk": chunk, "spec_k": spec_k,
                       "trace": True},
        "traced_tp2_all": {"tp": 2, "paged_kv": True,
                           "prefix_cache": cache,
                           "prefill_chunk": chunk, "spec_k": spec_k,
                           "trace": True},
    }
    # workload A: shared system prompt (load_gen's generator — one
    # request per "client", every prompt shares the prefix)
    mean_len = min(64, max_len - n_new - spec_k - 1)
    grid = lm_prompts(requests, 1, vocab=vocab, mean_len=mean_len,
                      shared_frac=0.6,
                      max_len=max_len - n_new - spec_k - 1, seed=11)
    shared = [grid[(ci, 0)] for ci in range(requests)]
    # workload B: repetitive text (prompt-lookup's home turf)
    rep = repetitive_prompts(requests, vocab,
                             min(48, max_len - n_new - spec_k - 1))
    # workload C: mixed lengths (where per-lane paging pays)
    mixed = mixed_length_prompts(
        requests, vocab, max(4, chunk // 2),
        max(chunk, (max_len - n_new - spec_k - 1) // 2))
    results = {"model": {"vocab": vocab, "d_model": d_model,
                         "n_layers": n_layers, "max_len": max_len},
               "slots": slots, "n_new": n_new,
               "workloads": {}}

    def stream_summary():
        """Bank everything completed so far as ONE stdout JSON line —
        an outer watchdog kill keeps the last one (the bench.py
        per-leg streaming discipline)."""
        record, _ = summary_record(results)
        print(json.dumps(record), flush=True)

    # the lint_clean assertion leg first (ISSUE 17): cheap (<1s, no
    # engine), and a dirty tree should refuse the run up front rather
    # than after minutes of legs
    run_lint_leg(results)
    # the single-lane repetitive workload ISOLATES speculation: with
    # one slot the baseline is exactly 1 dispatch/token, so any value
    # below 1 is the draft acceptance and nothing else (multi-slot
    # continuous batching is already sub-1 across lanes)
    for wname, prompts, wslots in (
            ("shared_prefix", shared, slots),
            ("mixed_length", mixed, slots),
            ("repetitive", rep, slots),
            ("repetitive_single_lane", rep[:max(2, requests // 2)], 1)):
        expect = expected_rows(params, prompts, n_new, n_heads, max_len)
        fpt = decode_flops_per_token(
            vocab, d_model, n_layers,
            int(numpy.mean([len(p) for p in prompts])) + n_new // 2,
            n_heads=n_heads)
        legs = results["workloads"].setdefault(wname, {})
        for fname, kw in feature_sets.items():
            legs[fname] = run_leg(params, n_heads, max_len, prompts,
                                  n_new, expect, slots=wslots,
                                  flops_per_token=fpt, **kw)
            print("%s/%s: %s" % (wname, fname, json.dumps(legs[fname])),
                  file=sys.stderr)
            stream_summary()
    # the fixed-KV-memory acceptance leg: same bytes, how many lanes?
    results["fixed_kv_memory"] = fixed_kv_memory_comparison(
        params, n_heads, max_len, chunk, n_new, vocab,
        budget_slots=2 if smoke else 4, requests=requests * 2)
    stream_summary()
    # the replica-scaling acceptance leg (ISSUE 8): device-bound
    # regime, 1 engine vs 2 replicas on the same mixed-length traffic
    results["replica_scaling"] = replica_scaling_comparison(
        params, n_heads, max_len, chunk, n_new, vocab, slots=slots,
        requests=max(8, requests))
    stream_summary()
    # headline facts the acceptance criteria name
    lane1 = results["workloads"]["repetitive_single_lane"]
    sp_cache = results["workloads"]["shared_prefix"]["prefix_cache"]
    sp_paged = results["workloads"]["shared_prefix"]["paged_all"]
    sp_base = results["workloads"]["shared_prefix"]["baseline"]
    fixed = results["fixed_kv_memory"]
    results["headline"] = {
        "dispatches_per_token_plain_single_lane":
            lane1["baseline"]["dispatches_per_token"],
        "dispatches_per_token_speculative_single_lane":
            lane1["spec"]["dispatches_per_token"],
        # ISSUE 13: the fused-megastep acceptance pair (run_leg already
        # ASSERTED < 0.1 on these legs) and the measured waste of
        # early-exit masking
        "dispatches_per_token_megastep_single_lane":
            lane1["megastep"]["dispatches_per_token"],
        "dispatches_per_token_megastep_all_single_lane":
            lane1["megastep_all"]["dispatches_per_token"],
        "megastep_waste_frac_single_lane":
            lane1["megastep"]["megastep_waste_frac"],
        # ISSUE 19: the while-loop megastep acceptance pair (run_leg
        # already ASSERTED waste < 0.02 and dpt <= 0.062 on these
        # legs) plus the in-graph refill count on the ring leg
        "dispatches_per_token_whilestep_single_lane":
            lane1["whilestep"]["dispatches_per_token"],
        "dispatches_per_token_whilestep_all_single_lane":
            lane1["whilestep_all"]["dispatches_per_token"],
        "whilestep_waste_frac_single_lane":
            lane1["whilestep"]["megastep_waste_frac"],
        "whilestep_all_waste_frac_single_lane":
            lane1["whilestep_all"]["megastep_waste_frac"],
        "whilestep_all_refills_single_lane":
            lane1["whilestep_all"].get("megastep_refills", 0),
        "prefill_tokens_baseline": sp_base["prefill_tokens"],
        "prefill_tokens_prefix_cache": sp_cache["prefill_tokens"],
        "prefix_hit_tokens": sp_cache["prefix_hit_tokens"],
        "prefill_flops_saved_frac": round(
            1 - sp_cache["prefill_tokens"]
            / max(sp_base["prefill_tokens"], 1), 3),
        # ISSUE 6: zero-copy prefix sharing + fixed-memory lane count
        "kv_row_copies_contiguous_shared_prefix":
            sp_cache["kv_row_copies"],
        "kv_row_copies_paged_shared_prefix": sp_paged["kv_row_copies"],
        "kv_pages_referenced_shared_prefix":
            sp_paged["kv_pages_referenced"],
        "slots_at_fixed_kv_memory_ratio":
            fixed["slots_ratio_vs_contiguous"],
        # ISSUE 7: the kernel-vs-XLA MFU pair on the same workload
        # (identical on CPU where the kernel leg falls back — the
        # split is a TPU-session fact) plus the which-path evidence
        "mfu_paged_xla_shared_prefix":
            results["workloads"]["shared_prefix"]["paged"]["mfu"],
        "mfu_paged_kernel_shared_prefix":
            results["workloads"]["shared_prefix"]["paged_kernel"]
            ["mfu"],
        "attn_kernel_dispatches_shared_prefix":
            results["workloads"]["shared_prefix"]["paged_kernel"]
            ["attn_kernel_dispatches"],
        "attn_kernel_fallbacks_shared_prefix":
            results["workloads"]["shared_prefix"]["paged_kernel"]
            ["attn_kernel_fallbacks"],
    }
    # ISSUE 8 headline: replica scaling on the mixed-length workload
    # (the acceptance ratio) + client-relevant balance on shared_prefix
    ml = results["workloads"]["mixed_length"]
    if "skipped" not in ml["replicas2"]:
        # the RAW shared-core ratio against the SAME engine config
        # single-replica ('paged' == replicas2 minus the router) —
        # honest about core contention on a dryrun box; the
        # acceptance ratio is the device-bound replica_scaling leg's
        results["headline"]["replicas2_speedup_mixed_length_raw"] = \
            round(ml["replicas2"]["tokens_per_sec"]
                  / max(ml["paged"]["tokens_per_sec"], 1e-9), 2)
    scaling = results.get("replica_scaling", {})
    if "skipped" not in scaling:
        results["headline"]["replicas2_speedup_mixed_length"] = \
            scaling["replicas2_speedup"]
        results["headline"]["replica_balance_ratio_mixed_length"] = \
            scaling["replica_balance_ratio"]
    sp2 = results["workloads"]["shared_prefix"]["replicas2"]
    if "skipped" not in sp2:
        results["headline"]["replica_balance_ratio_shared_prefix"] = \
            sp2["replica_balance_ratio"]
    tp_leg = results["workloads"]["shared_prefix"]["tp2"]
    if "skipped" not in tp_leg:
        results["headline"]["tp2_tokens_per_sec_shared_prefix"] = \
            tp_leg["tokens_per_sec"]
        results["headline"]["tp2_parity_vs_generate"] = \
            tp_leg["parity_vs_generate"]
    return results


def _latest_mfu(results):
    """The newest completed leg's MFU — the per-line column the
    streamed partial records carry (a watchdog kill still banks an
    MFU reading for whatever finished last)."""
    mfu = None
    for legs in (results.get("workloads") or {}).values():
        for leg in legs.values():
            if leg.get("mfu") is not None:
                mfu = leg["mfu"]
    fixed = results.get("fixed_kv_memory") or {}
    for key in ("contiguous", "paged"):
        leg = fixed.get(key)
        if leg and leg.get("mfu") is not None:
            mfu = leg["mfu"]
    return mfu


def summary_record(results):
    """Build (record, exit_code) for the driver's summary JSON line —
    same shape as ``bench.py::summary_record`` (metric/value/unit/
    vs_baseline/configs), with the metric-selection priority in ONE
    place so the per-leg partial stream and the final emit can never
    disagree: the fixed-KV-memory slot ratio once that leg has run
    (the ISSUE 6 acceptance headline), any paged shared-prefix leg's
    zero-row-copy fact before that, tokens/s of the newest completed
    leg as the early-partial fallback.  EVERY line carries an ``mfu``
    column (ISSUE 7): the newest completed leg's model-FLOPs
    utilization, so a killed run still banks the kernel-vs-XLA
    reading."""
    mfu = _latest_mfu(results)
    headline = results.get("headline") or {}
    if headline.get("dispatches_per_token_whilestep_single_lane") \
            is not None:
        # ISSUE 19 headline: the while-loop megastep's dispatches/
        # token against the K=16 scan record it must not regress
        return {
            "metric": "lm_whilestep_dispatches_per_token",
            "mfu": mfu,
            "value":
                headline["dispatches_per_token_whilestep_single_lane"],
            "unit": "dispatches/token",
            "vs_baseline": 0.062,
            "configs": results,
        }, 0
    if headline.get("dispatches_per_token_megastep_single_lane") \
            is not None:
        # ISSUE 13 headline: the fused-decode dispatches/token against
        # the 0.547 single-lane record the megastep replaces
        return {
            "metric": "lm_megastep_dispatches_per_token",
            "mfu": mfu,
            "value":
                headline["dispatches_per_token_megastep_single_lane"],
            "unit": "dispatches/token",
            "vs_baseline": 0.547,
            "configs": results,
        }, 0
    fixed = results.get("fixed_kv_memory") or {}
    if fixed.get("slots_ratio_vs_contiguous") is not None:
        return {
            "metric": "lm_paged_slots_at_fixed_kv_memory_ratio",
            "mfu": mfu,
            "value": fixed["slots_ratio_vs_contiguous"],
            "unit": "x_vs_contiguous",
            "vs_baseline": 1.0,
            "configs": results,
        }, 0
    workloads = results.get("workloads") or {}
    paged_sp = (workloads.get("shared_prefix") or {}).get("paged_all") \
        or (workloads.get("shared_prefix") or {}).get("paged")
    if paged_sp is not None:
        return {
            "metric": "lm_paged_shared_prefix_kv_row_copies",
            "mfu": mfu,
            "value": paged_sp["kv_row_copies"],
            "unit": "rows",
            "vs_baseline": None,
            "configs": results,
        }, 0
    latest = None
    for legs in workloads.values():
        for leg in legs.values():
            latest = leg
    if latest is not None:
        return {
            "metric": "lm_fastpath_tokens_per_sec",
            "mfu": mfu,
            "value": latest["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": None,
            "configs": results,
        }, 0
    return {
        "metric": "lm_fastpath_no_legs_completed",
        "mfu": mfu,
        "value": None,
        "unit": None,
        "vs_baseline": None,
        "configs": results,
    }, 1


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes (CI validation)")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--chunk", type=int, default=16,
                        help="prefill chunk size for the chunked legs")
    parser.add_argument("--cache", type=int, default=256,
                        help="prefix cache capacity (chunks)")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="speculative draft length")
    parser.add_argument("--n-new", type=int, default=32)
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the record here")
    parser.add_argument("--devices", type=int, default=0, metavar="N",
                        help="force an N-device CPU host platform "
                             "(xla_force_host_platform_device_count) "
                             "so the sharded legs (tp2/replicas2/"
                             "tp2_replicas2) can seat on a laptop/CI "
                             "box — CPU dryrun only, set before jax "
                             "initializes; ignored on real TPU hosts")
    args = parser.parse_args(argv)
    if args.devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=%d"
                % args.devices).strip()
    max_len = bench_max_len(args.smoke)
    if args.chunk < 1 or max_len % args.chunk:
        # the paged legs run unconditionally and LMEngine requires the
        # page size (= chunk) to divide max_len — refuse up front
        # instead of crashing mid-run with the summary unwritten
        parser.error("--chunk %d must divide max_len %d (paged legs)"
                     % (args.chunk, max_len))
    if args.spec_k and args.spec_k + 1 > args.chunk:
        # same up-front rule for the combined legs: LMEngine requires
        # the verify span (spec_k + 1) to fit in one chunk
        parser.error("--spec-k %d + 1 must fit in --chunk %d "
                     "(the combined 'all'/'paged_all' legs)"
                     % (args.spec_k, args.chunk))
    results = run_bench(smoke=args.smoke, slots=args.slots,
                        chunk=args.chunk, cache=args.cache,
                        spec_k=args.spec_k, n_new=args.n_new,
                        requests=args.requests, max_len=max_len)
    record, rc = summary_record(results)
    line = json.dumps(record)
    print(line)                  # final full record — last line wins
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
