"""Capture a jax.profiler trace of a bench config's train step and print a
per-op cost breakdown (top XLA ops by total device time).

Usage: python tools/trace_step.py [mnist|cifar|alexnet][_bf16] [outdir]

A ``_bf16`` suffix applies the measured conv-net fast path
(``functional.set_matmul_precision("bfloat16")`` — operand casts, fp32
accumulation) before building, so the captured trace matches the
``alexnet_bf16`` bench record (docs/PERF.md round-5 analysis predicted
~18 ms/step; the trace is the evidence).
"""
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

import numpy

# runnable as `python tools/trace_step.py` from anywhere: the repo root
# (where bench.py and veles_tpu/ live) is not on sys.path when the
# script dir is tools/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sync(x):
    import jax
    return numpy.asarray(jax.tree.leaves(x)[0]).ravel()[0]


def main():
    config = sys.argv[1] if len(sys.argv) > 1 else "alexnet"
    outdir = sys.argv[2] if len(sys.argv) > 2 else "/tmp/veles_trace_" + config
    import jax
    import bench

    if config.endswith("_bf16"):
        from veles_tpu.ops import functional as F
        F.set_matmul_precision("bfloat16")
        config = config[:-len("_bf16")]
    if config == "mnist":
        wf = bench.build_mnist(60000, 10000, 100)
    elif config == "cifar":
        wf = bench.build_cifar(50000, 10000, 100)
    else:
        wf = bench.build_alexnet(1024, 128, 128)

    runner = wf._fused_runner
    train_epoch, _ = runner.epoch_fns()
    loader = wf.loader
    data = loader.original_data.devmem
    labels = loader.original_labels.devmem
    idx, mask = bench.epoch_plan_arrays(loader)
    from veles_tpu import prng
    rng = prng.get("dropout").key() if runner._has_stochastic else None

    # compile + warm
    state, totals = train_epoch(runner.state, data, labels, idx, mask,
                                rng=rng, step0=0)
    _sync(totals)
    begin = time.perf_counter()
    state, totals = train_epoch(state, data, labels, idx, mask,
                                rng=rng, step0=0)
    _sync(totals)
    steps = idx.shape[0]
    wall = time.perf_counter() - begin
    print("epoch wall %.1f ms, %d steps, %.2f ms/step"
          % (wall * 1e3, steps, wall / steps * 1e3))

    with jax.profiler.trace(outdir):
        state, totals = train_epoch(state, data, labels, idx, mask,
                                    rng=rng, step0=0)
        _sync(totals)

    # ---- parse the chrome trace: aggregate device-lane events by name
    paths = glob.glob(os.path.join(outdir, "plugins/profile/*/*.trace.json.gz"))
    if not paths:
        print("no trace found under", outdir)
        return 1
    path = max(paths, key=os.path.getmtime)
    with gzip.open(path, "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    # find device lanes (TPU pids); tid/pid metadata names them
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e["args"].get("name", "")
    device_pids = {pid for pid, name in pid_names.items()
                   if "TPU" in name or "/device" in name.lower()}
    totals_by_name = defaultdict(float)
    count_by_name = defaultdict(int)
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        dur = e.get("dur", 0.0)  # microseconds
        totals_by_name[name] += dur
        count_by_name[name] += 1
        total += dur
    print("\ndevice lanes: %s" % {p: pid_names[p] for p in device_pids})
    print("total device time in trace: %.1f ms over %d steps -> %.2f ms/step"
          % (total / 1e3, steps, total / 1e3 / steps))
    print("\n%-72s %10s %6s %6s" % ("op", "total_ms", "count", "pct"))
    for name, t in sorted(totals_by_name.items(), key=lambda kv: -kv[1])[:40]:
        print("%-72s %10.2f %6d %5.1f%%"
              % (name[:72], t / 1e3, count_by_name[name],
                 100.0 * t / max(total, 1e-9)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
