"""Streamed summary-record schema guard (ISSUE 12 satellite).

Every bench in this repo streams one ``summary_record`` JSON line to
stdout after each completed leg — ``bench.py``, ``tools/lm_bench.py``,
``tools/chaos_bench.py``, ``tools/profile_ops.py``,
``tools/trace_report.py`` — and the driver (plus ``bench_report.py``
and the TPU-session tooling) parses the LAST line, so a silent schema
drift in any one tool breaks evidence collection without failing
anything.  This checker makes the shared contract executable:

- REQUIRED KEYS: every record carries ``metric`` (str), ``value``,
  ``unit``, ``vs_baseline`` and ``configs`` — exactly the bench.py
  shape.
- JSON-CLEAN: the record round-trips through ``json.dumps`` (no numpy
  scalars, no NaN/Infinity — strict parsers reject them).

Two modes:

- BUILTIN (default, <30s, rides tier-1 via ``tests/test_tools.py``):
  import each tool and validate the record its ``summary_record``
  produces for an EMPTY results dict — the worst-case partial stream a
  watchdog kill can leave — plus ``profile_ops``'s streamed line.
- FILE (``--file runs.jsonl``): validate every line of a captured
  stream (a bench's stdout), so a real run's records can be audited
  after the fact.

Exit 0 when every record conforms; 1 with one problem per line
otherwise.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
sys.path.insert(0, TOOLS_DIR)
sys.path.insert(0, REPO)

#: the shared record contract every streamed summary line honors
REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline", "configs")


def check_record(record, where="record"):
    """Problems with one parsed record (empty list = conforming)."""
    problems = []
    if not isinstance(record, dict):
        return ["%s: not a JSON object (got %s)"
                % (where, type(record).__name__)]
    for key in REQUIRED_KEYS:
        if key not in record:
            problems.append("%s: missing required key %r" % (where, key))
    metric = record.get("metric")
    if "metric" in record and (not isinstance(metric, str) or not metric):
        problems.append("%s: metric must be a non-empty string (got %r)"
                        % (where, metric))
    try:
        # strict JSON: numpy scalars and NaN/Infinity both die here,
        # which is exactly what a downstream strict parser would do
        json.loads(json.dumps(record, allow_nan=False))
    except (TypeError, ValueError) as e:
        problems.append("%s: not strict-JSON-serializable: %s"
                        % (where, e))
    return problems


def check_line(line, where="line"):
    """Problems with one raw stream line."""
    line = line.strip()
    if not line:
        return []
    try:
        record = json.loads(line)
    except json.JSONDecodeError as e:
        return ["%s: does not parse as JSON: %s" % (where, e)]
    return check_record(record, where)


def check_stream(text, where="stream"):
    problems = []
    for i, line in enumerate(text.splitlines(), start=1):
        problems.extend(check_line(line, "%s:%d" % (where, i)))
    return problems


def _builtin_records():
    """(where, record) pairs from every streaming tool's
    summary-record builder, fed the empty-results worst case (what a
    watchdog kill right after startup leaves) — importing the tool IS
    part of the check (an ImportError is a failed record source)."""
    out = []

    import bench
    out.append(("bench.summary_record({})", bench.summary_record({})[0]))

    import chaos_bench
    import lm_bench
    import trace_report
    out.append(("lm_bench.summary_record({})",
                lm_bench.summary_record({})[0]))
    # the megastep record path (ISSUE 13): a headline carrying the
    # fused-decode column must select the lm_megastep_* metric and
    # still conform to the shared schema
    ms_record = lm_bench.summary_record({
        "headline": {
            "dispatches_per_token_megastep_single_lane": 0.062}})[0]
    out.append(("lm_bench.summary_record(megastep headline)",
                ms_record))
    if ms_record.get("metric") != "lm_megastep_dispatches_per_token":
        out.append(("lm_bench.summary_record(megastep headline)",
                    {"metric": "",
                     "note": "megastep headline did not select the "
                             "lm_megastep_dispatches_per_token metric"}))
    out.append(("chaos_bench.summary_record({})",
                chaos_bench.summary_record({})[0]))
    out.append(("trace_report.summary_record({})",
                trace_report.summary_record({})[0]))

    # profile_ops streams directly — capture its line
    import profile_ops
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        profile_ops.stream_summary()
    line = buf.getvalue().strip().splitlines()[-1]
    out.append(("profile_ops.stream_summary()", json.loads(line)))
    return out


def check_builtin():
    """Validate every tool's empty-results record; returns problems."""
    problems = []
    try:
        records = _builtin_records()
    except Exception as e:   # noqa: BLE001 — an unimportable tool IS
        return ["collecting builtin records failed: %s: %s"
                % (type(e).__name__, e)]
    for where, record in records:
        problems.extend(check_record(record, where))
    return problems


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--file", default=None, metavar="JSONL",
                        help="validate every line of this captured "
                             "stream instead of the builtin tool check")
    args = parser.parse_args(argv)
    if args.file:
        with open(args.file, "r", encoding="utf-8") as f:
            problems = check_stream(f.read(), args.file)
        checked = "stream %s" % args.file
    else:
        problems = check_builtin()
        checked = "builtin summary_record sources"
    for p in problems:
        print("PROBLEM: %s" % p, file=sys.stderr)
    print(json.dumps({"checked": checked,
                      "problems": len(problems)}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
