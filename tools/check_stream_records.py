"""Streamed summary-record schema guard (ISSUE 12 satellite).

Every bench in this repo streams one ``summary_record`` JSON line to
stdout after each completed leg — ``bench.py``, ``tools/lm_bench.py``,
``tools/chaos_bench.py``, ``tools/profile_ops.py``,
``tools/trace_report.py`` — and the driver (plus ``bench_report.py``
and the TPU-session tooling) parses the LAST line, so a silent schema
drift in any one tool breaks evidence collection without failing
anything.  This checker makes the shared contract executable:

- REQUIRED KEYS: every record carries ``metric`` (str), ``value``,
  ``unit``, ``vs_baseline`` and ``configs`` — exactly the bench.py
  shape.
- JSON-CLEAN: the record round-trips through ``json.dumps`` (no numpy
  scalars, no NaN/Infinity — strict parsers reject them).

Two modes:

- BUILTIN (default, <30s, rides tier-1 via ``tests/test_tools.py``):
  import each tool and validate the record its ``summary_record``
  produces for an EMPTY results dict — the worst-case partial stream a
  watchdog kill can leave — plus ``profile_ops``'s streamed line.
- FILE (``--file runs.jsonl``): validate every line of a captured
  stream (a bench's stdout), so a real run's records can be audited
  after the fact.

Exit 0 when every record conforms; 1 with one problem per line
otherwise.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
sys.path.insert(0, TOOLS_DIR)
sys.path.insert(0, REPO)

#: the shared record contract every streamed summary line honors
REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline", "configs")

#: the ``GET /timeseries.json`` payload contract (ISSUE 14) — what
#: tools/slo_report.py and the TPU-session tooling join on
TIMESERIES_KEYS = ("name", "sampled_at", "interval_s", "window_s",
                   "samples", "series")
#: the ``GET /slo.json`` payload contract
SLO_KEYS = ("name", "sampled_at", "windows_s", "worst_state",
            "worst_state_name", "pages_total", "objectives")
#: every objective row in /slo.json ("held" = the state was carried
#: by the min_events gate rather than computed from fresh evidence)
SLO_OBJECTIVE_KEYS = ("source", "objective", "kind", "target",
                      "state", "state_name", "held", "burn_rates")


def check_payload(payload, required, where):
    """Problems with one endpoint payload: required keys + strict
    JSON (the shared shape rule, applied to the ISSUE 14 endpoints)."""
    problems = []
    if not isinstance(payload, dict):
        return ["%s: not a JSON object (got %s)"
                % (where, type(payload).__name__)]
    for key in required:
        if key not in payload:
            problems.append("%s: missing required key %r"
                            % (where, key))
    try:
        json.loads(json.dumps(payload, allow_nan=False))
    except (TypeError, ValueError) as e:
        problems.append("%s: not strict-JSON-serializable: %s"
                        % (where, e))
    return problems


def check_timeseries_payload(payload, where="timeseries.json"):
    """The /timeseries.json shape: top-level keys, and every series
    row carries a known kind with that kind's windowed fields."""
    problems = check_payload(payload, TIMESERIES_KEYS, where)
    for name, row in (payload.get("series") or {}).items():
        w = "%s series %r" % (where, name)
        kind = row.get("kind")
        if kind == "counter":
            need = ("last", "delta", "rate_per_s", "span_s")
        elif kind == "gauge":
            need = ("last", "min", "max", "mean")
        elif kind == "hist":
            need = ("count_delta", "rate_per_s", "p50", "p95",
                    "bounds")
        else:
            problems.append("%s: unknown kind %r" % (w, kind))
            continue
        for key in need:
            if key not in row:
                problems.append("%s: %s row missing %r"
                                % (w, kind, key))
    return problems


def check_slo_payload(payload, where="slo.json"):
    problems = check_payload(payload, SLO_KEYS, where)
    for row in (payload.get("objectives") or []):
        w = "%s objective %r" % (where, row.get("objective"))
        for key in SLO_OBJECTIVE_KEYS:
            if key not in row:
                problems.append("%s: missing %r" % (w, key))
        for b in row.get("burn_rates", []):
            for key in ("window_s", "burn", "error_ratio", "events"):
                if key not in b:
                    problems.append("%s: burn row missing %r"
                                    % (w, key))
    return problems


def _builtin_payload_problems():
    """Exercise the ISSUE 14 payload shapes against LIVE producers: a
    tiny in-process TimeSeriesStore + SLOMonitor (no jax, <1s), so a
    schema drift in either endpoint fails tier-1 loudly."""
    from veles_tpu.serving.metrics import ServingMetrics
    from veles_tpu.serving.slo import SLOMonitor
    from veles_tpu.serving.timeseries import TimeSeriesStore
    m = ServingMetrics("schema_probe")
    store = TimeSeriesStore(interval_s=0.05, capacity=16)
    store.add_source(m)
    problems = []
    for i in range(3):
        m.record_enqueue()
        m.record_response(0.004 * (i + 1))
        m.record_ttft(0.01)
        m.record_decode_step(0.002)
        m.set_gauge("queue_depth", i)
        store.sample_once()
    problems.extend(check_timeseries_payload(
        store.snapshot(window_s=60.0),
        "TimeSeriesStore.snapshot()"))
    monitor = SLOMonitor(store, SLOMonitor.default_objectives(),
                         windows_s=(5.0, 30.0), min_events=1)
    monitor.sample_once()
    problems.extend(check_slo_payload(monitor.snapshot(),
                                      "SLOMonitor.snapshot()"))
    return problems


def check_record(record, where="record"):
    """Problems with one parsed record (empty list = conforming)."""
    problems = []
    if not isinstance(record, dict):
        return ["%s: not a JSON object (got %s)"
                % (where, type(record).__name__)]
    for key in REQUIRED_KEYS:
        if key not in record:
            problems.append("%s: missing required key %r" % (where, key))
    metric = record.get("metric")
    if "metric" in record and (not isinstance(metric, str) or not metric):
        problems.append("%s: metric must be a non-empty string (got %r)"
                        % (where, metric))
    try:
        # strict JSON: numpy scalars and NaN/Infinity both die here,
        # which is exactly what a downstream strict parser would do
        json.loads(json.dumps(record, allow_nan=False))
    except (TypeError, ValueError) as e:
        problems.append("%s: not strict-JSON-serializable: %s"
                        % (where, e))
    return problems


def check_line(line, where="line"):
    """Problems with one raw stream line."""
    line = line.strip()
    if not line:
        return []
    try:
        record = json.loads(line)
    except json.JSONDecodeError as e:
        return ["%s: does not parse as JSON: %s" % (where, e)]
    return check_record(record, where)


def check_stream(text, where="stream"):
    problems = []
    for i, line in enumerate(text.splitlines(), start=1):
        problems.extend(check_line(line, "%s:%d" % (where, i)))
    return problems


def _builtin_records():
    """(where, record) pairs from every streaming tool's
    summary-record builder, fed the empty-results worst case (what a
    watchdog kill right after startup leaves) — importing the tool IS
    part of the check (an ImportError is a failed record source)."""
    out = []

    import bench
    out.append(("bench.summary_record({})", bench.summary_record({})[0]))

    import chaos_bench
    import lm_bench
    import trace_report
    out.append(("lm_bench.summary_record({})",
                lm_bench.summary_record({})[0]))
    # the megastep record path (ISSUE 13): a headline carrying the
    # fused-decode column must select the lm_megastep_* metric and
    # still conform to the shared schema
    ms_record = lm_bench.summary_record({
        "headline": {
            "dispatches_per_token_megastep_single_lane": 0.062}})[0]
    out.append(("lm_bench.summary_record(megastep headline)",
                ms_record))
    if ms_record.get("metric") != "lm_megastep_dispatches_per_token":
        out.append(("lm_bench.summary_record(megastep headline)",
                    {"metric": "",
                     "note": "megastep headline did not select the "
                             "lm_megastep_dispatches_per_token metric"}))
    # the whilestep record path (ISSUE 19): the while-loop headline
    # must WIN over the scan megastep column and conform to the shape
    ws_record = lm_bench.summary_record({
        "headline": {
            "dispatches_per_token_megastep_single_lane": 0.062,
            "dispatches_per_token_whilestep_single_lane": 0.058,
            "whilestep_waste_frac_single_lane": 0.0}})[0]
    out.append(("lm_bench.summary_record(whilestep headline)",
                ws_record))
    if ws_record.get("metric") != "lm_whilestep_dispatches_per_token":
        out.append(("lm_bench.summary_record(whilestep headline)",
                    {"metric": "",
                     "note": "whilestep headline did not select the "
                             "lm_whilestep_dispatches_per_token "
                             "metric"}))
    out.append(("chaos_bench.summary_record({})",
                chaos_bench.summary_record({})[0]))
    out.append(("trace_report.summary_record({})",
                trace_report.summary_record({})[0]))

    out.extend(_lint_records())

    import slo_report
    out.append(("slo_report.summary_record({})",
                slo_report.summary_record({})[0]))
    # the verdict-bearing shape must select the paging-objective
    # metric (the acceptance signal downstream tooling keys on)
    slo_rec = slo_report.summary_record(
        {"verdicts": [{"state_name": "page"}]})[0]
    out.append(("slo_report.summary_record(verdicts)", slo_rec))
    if slo_rec.get("metric") != "slo_objectives_paging":
        out.append(("slo_report.summary_record(verdicts)",
                    {"metric": "",
                     "note": "verdict results did not select the "
                             "slo_objectives_paging metric"}))

    # profile_ops streams directly — capture its line
    import profile_ops
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        profile_ops.stream_summary()
    line = buf.getvalue().strip().splitlines()[-1]
    out.append(("profile_ops.stream_summary()", json.loads(line)))
    return out


def _lint_records():
    """veles_lint's streamed records (ISSUE 15/17): the empty-results
    worst case, a populated run, and both faces of the bench-leg
    ``lint_clean`` record (lm_bench/chaos_bench stream it before
    their first real leg) — no jax import, <1s."""
    import veles_lint
    return [
        ("veles_lint.summary_record({})",
         veles_lint.summary_record({})[0]),
        ("veles_lint.summary_record(populated)",
         veles_lint.summary_record(
             {"findings": 2, "stats": {"files": 11,
                                       "suppressions": 3}})[0]),
        ("veles_lint.clean_record(clean)",
         veles_lint.clean_record(0, {"files": 11, "wall_s": 0.5})[0]),
        ("veles_lint.clean_record(dirty)",
         veles_lint.clean_record(3, {"files": 11, "wall_s": 0.5})[0]),
    ]


#: tools checkable WITHOUT importing the jax-heavy benches — the <1s
#: ``--tool`` mode (tests/test_lint.py rides it)
FAST_TOOLS = {"veles_lint": _lint_records}


def check_tool(name):
    """Validate one fast tool's records only (no bench imports);
    returns problems."""
    if name not in FAST_TOOLS:
        return ["unknown fast tool %r (one of %r)"
                % (name, sorted(FAST_TOOLS))]
    problems = []
    try:
        records = FAST_TOOLS[name]()
    except Exception as e:   # noqa: BLE001 — an unimportable tool IS
        return ["collecting %s records failed: %s: %s"
                % (name, type(e).__name__, e)]
    for where, record in records:
        problems.extend(check_record(record, where))
    return problems


def check_builtin():
    """Validate every tool's empty-results record; returns problems."""
    problems = []
    try:
        records = _builtin_records()
    except Exception as e:   # noqa: BLE001 — an unimportable tool IS
        return ["collecting builtin records failed: %s: %s"
                % (type(e).__name__, e)]
    for where, record in records:
        problems.extend(check_record(record, where))
    try:
        problems.extend(_builtin_payload_problems())
    except Exception as e:   # noqa: BLE001 — a broken producer IS
        problems.append("collecting builtin payloads failed: %s: %s"
                        % (type(e).__name__, e))
    return problems


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--file", default=None, metavar="JSONL",
                        help="validate every line of this captured "
                             "stream instead of the builtin tool check")
    parser.add_argument("--tool", default=None, metavar="NAME",
                        help="validate only this fast tool's records "
                             "(no bench imports, <1s): one of %s"
                             % sorted(FAST_TOOLS))
    args = parser.parse_args(argv)
    if args.file:
        with open(args.file, "r", encoding="utf-8") as f:
            problems = check_stream(f.read(), args.file)
        checked = "stream %s" % args.file
    elif args.tool:
        problems = check_tool(args.tool)
        checked = "fast tool %s" % args.tool
    else:
        problems = check_builtin()
        checked = "builtin summary_record sources"
    for p in problems:
        print("PROBLEM: %s" % p, file=sys.stderr)
    print(json.dumps({"checked": checked,
                      "problems": len(problems)}))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
