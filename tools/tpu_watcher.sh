#!/bin/bash
# TPU-tunnel recovery watcher (VERDICT r4 task 1: "keep it armed from
# minute one").  Probes the device with bench.py's timeout-bounded probe
# worker; the moment the tunnel answers, runs the FULL bench ladder and
# the real-IDX convergence tool, then exits so the session is notified.
#
# Usage: bash tools/tpu_watcher.sh [interval_seconds]
set -u
cd "$(dirname "$0")/.."
# Default interval is deliberately SPARSE: the round-5 sessions showed
# that sub-10-minute probe cycles can keep a wedged relay wedged (every
# abandoned probe claim is a client dying mid-claim), while every
# observed recovery landed during a probe-quiet gap.  Quiet beats eager.
INTERVAL="${1:-3600}"
OUT=bench_r5_tpu
echo "[watcher] started $(date -u +%FT%TZ), probing every ${INTERVAL}s"
while true; do
    # patient probe: a probe that gives up and exits right as the relay
    # finally grants its claim is itself a client dying mid-claim — the
    # wedge-arming event.  300 s of patience means a slow-recovering
    # relay's grant gets USED (the probe completes) instead of abandoned,
    # and the long interval keeps abandoned-claim pressure low.
    probe=$(VELES_BENCH_PROBE_S=300 timeout 420 \
            python bench.py --worker __probe__ 2>/dev/null | tail -1)
    if echo "$probe" | grep -q '"ok": true'; then
        echo "[watcher] tunnel ALIVE at $(date -u +%FT%TZ) — running bench"
        python bench.py >"${OUT}.out" 2>"${OUT}.err"
        echo "[watcher] bench rc=$? at $(date -u +%FT%TZ)"
        VELES_CONV_CONFIG_TIMEOUT_S=1500 timeout 7200 \
            python tools/convergence.py \
            >convergence_r5_tpu.out 2>convergence_r5_tpu.err
        echo "[watcher] convergence rc=$? at $(date -u +%FT%TZ)"
        # the 3 TPU-only Pallas PRNG kernel tests (skip off-hardware):
        # run them once on the real device (VERDICT r4 task 3).
        # VELES_TEST_TPU=1 tells conftest to leave the platform alone.
        VELES_TEST_TPU=1 timeout 1200 python -m pytest \
            tests/test_pallas.py -q -rs \
            >pallas_tpu_r5.out 2>&1
        echo "[watcher] pallas-tpu rc=$? at $(date -u +%FT%TZ)"
        # post-bf16 AlexNet trace: the committed round-4 artifact is
        # fp32-HIGHEST; this one is the evidence for the bf16 default
        # (predicted ~18 ms/step in docs/PERF.md).
        timeout 1800 python tools/trace_step.py alexnet_bf16 \
            /tmp/veles_trace_alexnet_bf16 \
            >trace_alexnet_bf16_r5.out 2>&1
        echo "[watcher] bf16-trace rc=$? at $(date -u +%FT%TZ)"
        # pass 2: re-run ONLY the configs pass 1 failed (wedge-killed or
        # skipped-behind-a-wedge).  By now the relay has had the whole
        # convergence+pallas+trace interval to shed a wedged claim, and
        # configs that did complete earlier populated the compile cache,
        # so their programs are off the relay's critical path entirely.
        # Nothing failed -> no pass 2 (don't double device time).
        FAILED=$(python - "$OUT.out" <<'PYEOF'
import json, sys
try:
    line = [l for l in open(sys.argv[1]) if l.startswith("{")][-1]
    cfgs = json.loads(line).get("configs", {})
except Exception:
    sys.exit(0)
names = sorted({k[:-len("_error")] for k in cfgs if k.endswith("_error")})
print(",".join(names))
PYEOF
)
        if [ -n "$FAILED" ]; then
            echo "[watcher] pass2 re-running failed configs: $FAILED"
            python bench.py --configs "$FAILED" \
                >"${OUT}_pass2.out" 2>"${OUT}_pass2.err"
            echo "[watcher] bench pass2 rc=$? at $(date -u +%FT%TZ)"
        else
            echo "[watcher] pass2 not needed (all configs landed)"
        fi
        exit 0
    fi
    echo "[watcher] tunnel dead at $(date -u +%FT%TZ)"
    sleep "$INTERVAL"
done
