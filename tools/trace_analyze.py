"""Per-op breakdown of a Chrome-trace (.trace.json[.gz]) captured by
tools/trace_step.py — the committed-artifact half of the perf loop
(VERDICT r4 task 2): aggregate XLA-op durations by HLO identity, compute
per-step cost, achieved TFLOP/s and HBM GB/s per op, and classify each
as MXU-bound vs HBM-bound, so "where do the milliseconds go" is a table
in docs/PERF.md instead of a guess.

Usage::

    python tools/trace_analyze.py docs/traces/X.trace.json.gz [--steps N]
    python tools/trace_analyze.py X.trace.json.gz --markdown

The outer ``while`` op (the lax.scan over training steps) is excluded
from aggregation — its children are on the same timeline — and every
count is divided by the number of scan iterations so the table reads
"per training step".
"""

from __future__ import annotations

import argparse
import collections
import gzip
import json


def load_events(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        trace = json.load(f)
    return trace["traceEvents"]


def xla_ops(events):
    """Complete ('X') events on every thread named 'XLA Ops'."""
    threads = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            threads[(e["pid"], e.get("tid"))] = e["args"]["name"]
    return [e for e in events if e.get("ph") == "X"
            and threads.get((e["pid"], e.get("tid"))) == "XLA Ops"]


def analyze(path, steps=None):
    """Aggregate per-op rows.  ``steps`` = scan iterations per while-op
    execution; inferred from the most common op count inside the while
    when not given."""
    ops = xla_ops(load_events(path))
    whiles = [e for e in ops if e["args"].get("hlo_category") == "while"]
    inner = [e for e in ops if e["args"].get("hlo_category") != "while"]
    n_while = max(len(whiles), 1)

    rows = {}
    for e in inner:
        a = e.get("args", {})
        key = e["name"]
        r = rows.setdefault(key, {
            "op": key, "category": a.get("hlo_category", "?"),
            "count": 0, "dur_us": 0.0, "flops": 0, "bytes": 0,
            "shape": a.get("shape_with_layout", ""),
        })
        r["count"] += 1
        r["dur_us"] += e["dur"]
        r["flops"] += int(a.get("model_flops", 0) or 0)
        r["bytes"] += int(a.get("bytes_accessed", 0) or 0)

    if steps is None:
        # per-step op instances repeat once per scan iteration (whatever
        # number of while executions those iterations are spread over);
        # the MODAL execution count of the heavy ops IS the total number
        # of training steps in the capture
        counts = collections.Counter(
            r["count"] for r in rows.values() if r["dur_us"] > 1000)
        steps = counts.most_common(1)[0][0] if counts else 1

    total_us = sum(r["dur_us"] for r in rows.values())
    out = []
    for r in sorted(rows.values(), key=lambda r: -r["dur_us"]):
        sec = r["dur_us"] / 1e6
        out.append({
            **r,
            "ms_per_step": r["dur_us"] / 1e3 / steps,
            "pct": 100.0 * r["dur_us"] / total_us,
            "tflops": (r["flops"] / sec / 1e12) if sec else 0.0,
            "gbps": (r["bytes"] / sec / 1e9) if sec else 0.0,
        })
    return {"rows": out, "steps": steps, "n_while": n_while,
            "total_ms_per_step": total_us / 1e3 / steps}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("trace")
    p.add_argument("--steps", type=int, default=None,
                   help="scan iterations per while execution (inferred "
                        "from op counts when omitted)")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--markdown", action="store_true")
    args = p.parse_args()
    res = analyze(args.trace, args.steps)
    rows = res["rows"][:args.top]
    shown = sum(r["ms_per_step"] for r in rows)
    print("# %d while execution(s) x %d scan steps; device total "
          "%.2f ms/step (top %d ops below: %.2f ms)"
          % (res["n_while"], res["steps"], res["total_ms_per_step"],
             args.top, shown))
    if args.markdown:
        print("| op | category | ms/step | % | TF/s | GB/s |")
        print("|---|---|---|---|---|---|")
        for r in rows:
            print("| %s | %s | %.3f | %.1f | %.1f | %.0f |"
                  % (r["op"], r["category"], r["ms_per_step"],
                     r["pct"], r["tflops"], r["gbps"]))
    else:
        for r in rows:
            print("%8.3f ms/step %5.1f%% %7.1f TF/s %6.0f GB/s  %-28s %s"
                  % (r["ms_per_step"], r["pct"], r["tflops"], r["gbps"],
                     r["op"], r["category"]))


if __name__ == "__main__":
    main()
