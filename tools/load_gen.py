"""Closed-loop HTTP load generator for the serving endpoints.

Drives N client threads against a veles_tpu serving port (restful_api's
``/predict``), each looping POST → wait-for-reply → POST (closed loop),
optionally paced to a target aggregate QPS.  Collects per-request
latency and status counts and prints one JSON summary — the evidence
side of the serving subsystem (ISSUE 1): mean dispatch batch size and
429 behavior come from the server's ``/metrics.json``, client-side
latency percentiles from here.

Standalone::

    python tools/load_gen.py --url http://127.0.0.1:8180/predict \
        --payload '{"input": [[0.0, 0.0, 0.0, 0.0]]}' \
        --clients 8 --requests 50 [--qps 100] [--duration 5]

Importable: :func:`run_load` is used by the serving load tests
(``tests/test_serving.py``).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.error
import urllib.request


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def run_load(url, payload, clients=8, requests_per_client=20, qps=None,
             duration=None, timeout=30.0, payload_fn=None):
    """Run the closed-loop load; returns the summary dict.

    ``payload`` is the JSON body every request posts; ``payload_fn``
    (client_index, request_index) -> dict overrides it per request (so
    correctness checks can give every request distinct input).
    ``duration`` (seconds) replaces the per-client request count;
    ``qps`` paces the AGGREGATE request rate across all clients.
    """
    interval = clients / qps if qps else 0.0
    stop_at = None
    results = []   # (status_code, latency_s, body_or_None)
    lock = threading.Lock()

    def client(ci):
        n = 0
        while True:
            if stop_at is not None:
                if time.monotonic() >= stop_at:
                    return
            elif n >= requests_per_client:
                return
            body = payload_fn(ci, n) if payload_fn is not None else payload
            data = json.dumps(body).encode()
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            t0 = time.monotonic()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    out = json.loads(resp.read())
                    code = resp.status
            except urllib.error.HTTPError as e:
                try:
                    out = json.loads(e.read())
                except Exception:   # noqa: BLE001 — non-JSON error body
                    out = None
                code = e.code
            except Exception:   # noqa: BLE001 — connection-level failure
                out, code = None, 0
            dt = time.monotonic() - t0
            with lock:
                results.append((code, dt, out))
            n += 1
            if interval and dt < interval:
                time.sleep(interval - dt)

    if duration is not None:
        stop_at = time.monotonic() + duration
    t_start = time.monotonic()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start

    by_status = {}
    for code, _, _ in results:
        by_status[str(code)] = by_status.get(str(code), 0) + 1
    lats = sorted(dt for code, dt, _ in results if code == 200)
    return {
        "url": url,
        "clients": clients,
        "sent": len(results),
        "ok": len(lats),
        "by_status": by_status,
        "wall_s": wall,
        "achieved_qps": len(results) / wall if wall > 0 else 0.0,
        "latency_s": {
            "mean": sum(lats) / len(lats) if lats else 0.0,
            "p50": _percentile(lats, 0.50),
            "p95": _percentile(lats, 0.95),
            "p99": _percentile(lats, 0.99),
            "max": lats[-1] if lats else 0.0,
        },
        "responses": [r for _, _, r in results],
    }


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--url", required=True,
                        help="serving endpoint, e.g. "
                             "http://127.0.0.1:8180/predict")
    parser.add_argument("--payload", required=True,
                        help="JSON request body (or @file to read one)")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--requests", type=int, default=20,
                        metavar="N", help="requests per client")
    parser.add_argument("--qps", type=float, default=None,
                        help="target aggregate request rate (default: "
                             "unpaced closed loop)")
    parser.add_argument("--duration", type=float, default=None,
                        metavar="SECONDS",
                        help="run for a wall-clock window instead of a "
                             "fixed request count")
    parser.add_argument("--timeout", type=float, default=30.0)
    args = parser.parse_args(argv)
    raw = args.payload
    if raw.startswith("@"):
        with open(raw[1:], encoding="utf-8") as f:
            raw = f.read()
    summary = run_load(args.url, json.loads(raw), clients=args.clients,
                       requests_per_client=args.requests, qps=args.qps,
                       duration=args.duration, timeout=args.timeout)
    summary.pop("responses")     # bodies are for the tests, not the CLI
    json.dump(summary, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
