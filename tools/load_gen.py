"""Closed-loop HTTP load generator for the serving endpoints.

Drives N client threads against a veles_tpu serving port (restful_api's
``/predict``), each looping POST → wait-for-reply → POST (closed loop),
optionally paced to a target aggregate QPS.  Collects per-request
latency and status counts and prints one JSON summary — the evidence
side of the serving subsystem (ISSUE 1): mean dispatch batch size and
429 behavior come from the server's ``/metrics.json``, client-side
latency percentiles from here.

LM MODE (ISSUE 4) makes this the ONE closed-loop generator the serving
and LM benches share: ``--lm`` synthesizes token prompts with a
configurable length distribution and a SHARED leading prefix across a
fraction of requests (the system-prompt shape the radix prefix cache
exists for), posts them as ``{"input": [[tok, ...]], "n_new": N}``
against ``serve_lm``, and reports per-request generated-token counts
and token throughput (client-side tokens/s) alongside the latency
percentiles.  ``tools/lm_bench.py`` imports the same prompt generator
so benchmark prompts and load-test prompts can never drift.

Failure accounting is split BY CLASS (ISSUE 10): the summary's
``failures`` dict separates timeouts, 429s, 503s, connection drops and
other HTTP errors, and ``shed_not_errored`` is True exactly when every
non-200 was a graceful shed (429/503) — what the chaos harness asserts
after a fault-injection run.

LM replies' per-row ``weights_version`` stamps (ISSUE 11) aggregate
into ``lm.weights_versions`` — per-version request counts plus
first/last-seen completion offsets — so a zero-downtime weight swap's
client-observed cutover is measurable from outside the server, the
way ``lm.per_replica_requests`` measures router balance.

Standalone::

    python tools/load_gen.py --url http://127.0.0.1:8180/predict \
        --payload '{"input": [[0.0, 0.0, 0.0, 0.0]]}' \
        --clients 8 --requests 50 [--qps 100] [--duration 5]

    python tools/load_gen.py --url http://127.0.0.1:8180/predict \
        --lm --lm-vocab 16 --lm-mean-len 48 --lm-shared-frac 0.5 \
        --lm-n-new 32 --clients 8 --requests 20

Importable: :func:`run_load` / :func:`run_lm_load` /
:func:`lm_prompts` are used by the serving tests and benches.
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import threading
import time
import urllib.error
import urllib.request


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def run_load(url, payload, clients=8, requests_per_client=20, qps=None,
             duration=None, timeout=30.0, payload_fn=None):
    """Run the closed-loop load; returns the summary dict.

    ``payload`` is the JSON body every request posts; ``payload_fn``
    (client_index, request_index) -> dict overrides it per request (so
    correctness checks can give every request distinct input).
    ``duration`` (seconds) replaces the per-client request count;
    ``qps`` paces the AGGREGATE request rate across all clients.
    """
    interval = clients / qps if qps else 0.0
    stop_at = None
    results = []   # (status_code, latency_s, body, client, req, class)
    lock = threading.Lock()

    def failure_class(code, exc):
        """ISSUE 10 satellite: bucket every outcome so chaos runs can
        assert "shed, not errored" — timeouts vs 429 vs 503 vs
        connection drops vs other HTTP errors."""
        if code == 200:
            return "ok"
        if code == 429:
            return "http_429"
        if code == 503:
            return "http_503"
        if code:
            return "http_other"
        reason = getattr(exc, "reason", exc)
        if isinstance(reason, (socket.timeout, TimeoutError)):
            return "timeout"
        return "connection"

    def client(ci):
        n = 0
        while True:
            if stop_at is not None:
                if time.monotonic() >= stop_at:
                    return
            elif n >= requests_per_client:
                return
            body = payload_fn(ci, n) if payload_fn is not None else payload
            data = json.dumps(body).encode()
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            t0 = time.monotonic()
            exc = None
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    out = json.loads(resp.read())
                    code = resp.status
            except urllib.error.HTTPError as e:
                try:
                    out = json.loads(e.read())
                except Exception:   # noqa: BLE001 — non-JSON error body
                    out = None
                code = e.code
            except Exception as e:  # noqa: BLE001 — connection-level
                out, code, exc = None, 0, e
            dt = time.monotonic() - t0
            with lock:
                results.append((code, dt, out, ci, n,
                                failure_class(code, exc),
                                t0 - t_start))
            n += 1
            if interval and dt < interval:
                time.sleep(interval - dt)

    if duration is not None:
        stop_at = time.monotonic() + duration
    t_start = time.monotonic()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start

    by_status = {}
    failures = {"timeout": 0, "http_429": 0, "http_503": 0,
                "connection": 0, "http_other": 0}
    for code, _, _, _, _, klass, _ in results:
        by_status[str(code)] = by_status.get(str(code), 0) + 1
        if klass != "ok":
            failures[klass] += 1
    lats = sorted(dt for code, dt, _, _, _, _, _ in results
                  if code == 200)
    return {
        "url": url,
        "clients": clients,
        "sent": len(results),
        "ok": len(lats),
        "by_status": by_status,
        # failure accounting BY CLASS (ISSUE 10 satellite): chaos runs
        # assert "shed (429/503), not errored (timeout/connection/5xx)"
        "failures": failures,
        "shed_not_errored": (failures["timeout"] == 0
                             and failures["connection"] == 0
                             and failures["http_other"] == 0),
        "wall_s": wall,
        "achieved_qps": len(results) / wall if wall > 0 else 0.0,
        "latency_s": {
            "mean": sum(lats) / len(lats) if lats else 0.0,
            "p50": _percentile(lats, 0.50),
            "p95": _percentile(lats, 0.95),
            "p99": _percentile(lats, 0.99),
            "max": lats[-1] if lats else 0.0,
        },
        "responses": [r for _, _, r, _, _, _, _ in results],
        #: per-request facts aligned with ``responses`` — LM mode reads
        #: these to pair each reply with its generating (client, index);
        #: ``t`` is the submit offset from the run start (seconds), so
        #: a weight-swap cutover is placeable on the run's timeline;
        #: ``request_id`` is the server's per-reply stamp (ISSUE 12) —
        #: the join key between client records, server traces
        #: (/trace.json) and log lines
        "records": [{"status": code, "latency_s": dt, "client": ci,
                     "req": n, "class": klass, "t": round(t, 6),
                     "request_id": (r or {}).get("request_id")
                     if isinstance(r, dict) else None}
                    for code, dt, r, ci, n, klass, t in results],
    }


def lm_prompts(clients, requests_per_client, vocab=16, mean_len=48,
               shared_frac=0.5, max_len=None, seed=0):
    """Synthesize the LM serving workload's prompts: lengths drawn from
    a lognormal around ``mean_len`` (the long-tail shape real prompt
    traffic has), each prompt = one SHARED prefix of
    ``int(mean_len * shared_frac)`` tokens (system prompt / few-shot
    header — what the radix prefix cache deduplicates) + a unique
    random tail.  Returns {(client, req): [tok, ...]} over the full
    client/request grid, deterministic in ``seed`` so benches and
    correctness checks can regenerate the same traffic."""
    import numpy
    rng = numpy.random.RandomState(seed)
    shared_len = max(0, int(mean_len * shared_frac))
    if max_len is not None:
        # the cap is a hard promise (servers size their KV cache by
        # it): the shared prefix must leave room for >= 1 unique tail
        # token, else every prompt would silently exceed max_len
        shared_len = min(shared_len, max(0, int(max_len) - 1))
    cap = int(max_len) if max_len is not None else 4 * mean_len
    cap = max(cap, shared_len + 1)
    shared = rng.randint(0, vocab, shared_len).tolist()
    out = {}
    for ci in range(clients):
        for n in range(requests_per_client):
            length = int(numpy.clip(
                rng.lognormal(numpy.log(max(mean_len, 2)), 0.35),
                shared_len + 1, cap))
            tail = rng.randint(0, vocab, max(1, length - shared_len))
            out[(ci, n)] = shared + tail.tolist()
    return out


def run_lm_load(url, clients=8, requests_per_client=20, vocab=16,
                mean_len=48, shared_frac=0.5, n_new=32, max_len=None,
                qps=None, duration=None, timeout=30.0, seed=0):
    """Closed-loop LM load: :func:`lm_prompts` traffic against a
    ``serve_lm`` endpoint, with per-request token accounting on top of
    :func:`run_load`'s latency summary — generated-token counts per
    request ("token streaming" viewed from the client), aggregate
    tokens/s, and TTFT-proxy stats (latency / tokens)."""
    prompts = lm_prompts(clients, requests_per_client, vocab=vocab,
                         mean_len=mean_len, shared_frac=shared_frac,
                         max_len=max_len, seed=seed)

    def payload_fn(ci, n):
        return {"input": [prompts[(ci, n % requests_per_client)]],
                "n_new": n_new}

    summary = run_load(url, None, clients=clients,
                       requests_per_client=requests_per_client,
                       qps=qps, duration=duration, timeout=timeout,
                       payload_fn=payload_fn)
    gen_counts, rates = [], []
    replica_counts = {}
    version_stats = {}
    for rec, resp in zip(summary["records"], summary["responses"]):
        if rec["status"] != 200 or not resp or "tokens" not in resp:
            continue
        prompt = prompts[(rec["client"],
                          rec["req"] % requests_per_client)]
        generated = len(resp["tokens"][0]) - len(prompt)
        gen_counts.append(generated)
        if rec["latency_s"] > 0:
            rates.append(generated / rec["latency_s"])
        # routed serving (ISSUE 8): serve_lm(replicas=N) stamps each
        # row with the replica that decoded it — aggregate the
        # CLIENT-observed placement so router skew is measurable from
        # outside the server
        for rid in resp.get("replicas", ()):
            key = str(rid)
            replica_counts[key] = replica_counts.get(key, 0) + 1
        # zero-downtime updates (ISSUE 11): each row is stamped with
        # the weights_version that decoded it — per-version request
        # counts plus first/last-seen completion times make a swap's
        # CLIENT-observed cutover measurable (mirrors the replica-
        # balance accounting above)
        done_at = rec["t"] + rec["latency_s"]
        for ver in resp.get("weights_version", ()):
            if ver is None:
                continue
            key = str(ver)
            st = version_stats.get(key)
            if st is None:
                st = version_stats[key] = {
                    "requests": 0, "first_seen_s": done_at,
                    "last_seen_s": done_at}
            st["requests"] += 1
            st["first_seen_s"] = round(
                min(st["first_seen_s"], done_at), 4)
            st["last_seen_s"] = round(
                max(st["last_seen_s"], done_at), 4)
    summary["lm"] = {
        "vocab": vocab, "mean_len": mean_len,
        "shared_frac": shared_frac, "n_new": n_new,
        "shared_prefix_len": max(0, int(mean_len * shared_frac)),
        "generated_tokens": int(sum(gen_counts)),
        "tokens_per_sec": (sum(gen_counts) / summary["wall_s"]
                           if summary["wall_s"] > 0 else 0.0),
        "per_request_tokens": {
            "mean": (sum(gen_counts) / len(gen_counts)
                     if gen_counts else 0.0),
            "min": min(gen_counts) if gen_counts else 0,
            "max": max(gen_counts) if gen_counts else 0,
        },
        "per_request_tokens_per_sec": {
            "mean": sum(rates) / len(rates) if rates else 0.0,
            "p50": _percentile(sorted(rates), 0.50),
        },
    }
    if version_stats:
        summary["lm"]["weights_versions"] = dict(
            sorted(version_stats.items()))
        summary["lm"]["per_version_requests"] = {
            v: st["requests"]
            for v, st in sorted(version_stats.items())}
    if replica_counts:
        # balance ratio: max/min requests per replica as THE CLIENT
        # saw them (1.0 = perfect spread; the acceptance criterion
        # bounds it on the shared-prefix workload).  Only replicas
        # that actually served appear in the counts, so a routed
        # fleet where every reply came from ONE replica is total
        # skew (a starved or drained sibling) — reported as null,
        # never as a perfect 1.0.
        summary["lm"]["per_replica_requests"] = dict(
            sorted(replica_counts.items()))
        summary["lm"]["replica_balance_ratio"] = (
            round(max(replica_counts.values())
                  / min(replica_counts.values()), 3)
            if len(replica_counts) > 1 else None)
    return summary


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--url", required=True,
                        help="serving endpoint, e.g. "
                             "http://127.0.0.1:8180/predict")
    parser.add_argument("--payload", default=None,
                        help="JSON request body (or @file to read one); "
                             "required unless --lm")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--requests", type=int, default=20,
                        metavar="N", help="requests per client")
    parser.add_argument("--qps", type=float, default=None,
                        help="target aggregate request rate (default: "
                             "unpaced closed loop)")
    parser.add_argument("--duration", type=float, default=None,
                        metavar="SECONDS",
                        help="run for a wall-clock window instead of a "
                             "fixed request count")
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument("--lm", action="store_true",
                        help="LM mode: synthesize token prompts "
                             "(length distribution + shared prefix) "
                             "against serve_lm and report token "
                             "throughput")
    parser.add_argument("--lm-vocab", type=int, default=16)
    parser.add_argument("--lm-mean-len", type=int, default=48,
                        metavar="TOKENS",
                        help="mean prompt length (lognormal tail)")
    parser.add_argument("--lm-shared-frac", type=float, default=0.5,
                        metavar="FRAC",
                        help="fraction of the mean length every prompt "
                             "shares as a common prefix (system-prompt "
                             "shape; what the prefix cache dedups)")
    parser.add_argument("--lm-n-new", type=int, default=32,
                        metavar="N", help="tokens to generate per request")
    parser.add_argument("--lm-max-len", type=int, default=None,
                        metavar="TOKENS", help="prompt length cap")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if args.lm:
        summary = run_lm_load(
            args.url, clients=args.clients,
            requests_per_client=args.requests, vocab=args.lm_vocab,
            mean_len=args.lm_mean_len, shared_frac=args.lm_shared_frac,
            n_new=args.lm_n_new, max_len=args.lm_max_len, qps=args.qps,
            duration=args.duration, timeout=args.timeout,
            seed=args.seed)
    else:
        if args.payload is None:
            parser.error("--payload is required without --lm")
        raw = args.payload
        if raw.startswith("@"):
            with open(raw[1:], encoding="utf-8") as f:
                raw = f.read()
        summary = run_load(args.url, json.loads(raw),
                           clients=args.clients,
                           requests_per_client=args.requests,
                           qps=args.qps, duration=args.duration,
                           timeout=args.timeout)
    summary.pop("responses")     # bodies are for the tests, not the CLI
    summary.pop("records")
    json.dump(summary, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
