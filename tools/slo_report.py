"""SLO report (ISSUE 14): burn-rate timelines and objective verdicts
from a captured ``/timeseries.json`` (+ optional ``/slo.json``).

Input is what the serving stack already exports — ``curl
:PORT/timeseries.json?window=600 > ts.json`` and ``curl
:PORT/slo.json > slo.json`` on a ``--serve-telemetry``/``--serve-slo``
server.  Two views:

- TIMELINE — per (source, objective) the error-budget burn rate
  RECOMPUTED at every captured sample over a sliding window, rendered
  as an ASCII strip (`` .:-=#`` scaled to the page threshold, ``!``
  beyond it) — how the burn evolved, not just where it ended.
  Availability/shed objectives replay the counter rings;
  latency objectives replay the histogram rings' cumulative bucket
  counts (the export carries them per point).
- VERDICT — the monitor's own state per objective from ``/slo.json``
  (ok/warn/page, burn per window, events), printed as a table.

A bench.py-style summary JSON line (metric/value/unit/vs_baseline/
configs) streams after each completed stage, last-line-wins, so the
driver and ``tools/check_stream_records.py`` treat this tool exactly
like every other bench.

Standalone::

    python tools/slo_report.py ts.json [--slo slo.json]
        [--window S] [--json FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: burn-intensity glyphs: index = min(burn / page_burn, 1) * (len-1);
#: '!' marks >= page_burn
GLYPHS = " .:-=#"


def load_json(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def _counter_deltas(points):
    """[(t, delta)] between consecutive cumulative points, clamped
    at zero (engine restarts)."""
    return [(b[0], max(0, b[1] - a[1]))
            for a, b in zip(points, points[1:])]


def _sources(ts):
    """Source keys present in a captured snapshot (series names are
    '<source>.<kind>.<field>')."""
    out = []
    for name in ts.get("series", {}):
        key = name.split(".counter.")[0].split(".gauge.")[0] \
                  .split(".hist.")[0].split(".ewma.")[0]
        if key not in out:
            out.append(key)
    return out


def _series(ts, name):
    s = ts.get("series", {}).get(name)
    return s.get("series", []) if s else []


def _window_sum(deltas, t, window_s):
    return sum(d for (td, d) in deltas if t - window_s < td <= t)


def burn_timeline(ts, source, objective, window_s):
    """[(t, burn)] for ``objective`` (a dict in the slo.json objective
    shape: name/kind/target[/series/threshold_s]) over ``source``'s
    captured rings, one point per sample, each over a trailing
    ``window_s``."""
    kind = objective["kind"]
    budget = 1.0 - float(objective["target"])
    if kind in ("availability", "shed_rate"):
        if kind == "availability":
            bad_names = ["%s.counter.errors" % source]
        else:
            bad_names = ["%s.counter.shed" % source,
                         "%s.counter.rejected" % source]
        ok = _counter_deltas(_series(
            ts, "%s.counter.responses" % source))
        bads = [_counter_deltas(_series(ts, n)) for n in bad_names]
        times = [t for (t, _) in ok] or [
            t for b in bads for (t, _) in b]
        out = []
        for t in times:
            bad = sum(_window_sum(b, t, window_s) for b in bads)
            good = _window_sum(ok, t, window_s)
            total = bad + good
            ratio = bad / total if total else 0.0
            out.append((t, ratio / budget))
        return out
    # latency: replay the histogram ring's cumulative buckets
    name = "%s.hist.%s" % (source, objective.get("series", "ttft"))
    s = ts.get("series", {}).get(name)
    if not s:
        return []
    bounds, pts = s.get("bounds", []), s.get("series", [])
    thr = float(objective.get("threshold_s", 0.0))
    # the LAST bound <= threshold is the 'good' cut — the same
    # conservative rounding TimeSeriesStore.count_in_window applies
    # (a threshold between bounds rounds DOWN; below every bound,
    # nothing counts as good).  NB "+Inf" PARSES to float inf — the
    # overflow bound never qualifies as a finite cut.
    cut = None
    for i, b in enumerate(bounds):
        try:
            bf = float(b)
        except ValueError:
            bf = float("inf")
        if bf != float("inf") and bf <= thr:
            cut = i
        else:
            break
    deltas = []
    for a, b in zip(pts, pts[1:]):
        if len(a) < 4 or len(b) < 4:
            continue
        total = max(0, b[1] - a[1])
        good = 0
        if cut is not None and cut < len(a[3]) and cut < len(b[3]):
            good = max(0, b[3][cut] - a[3][cut])
        deltas.append((b[0], max(0, total - good), total))
    out = []
    for t, _, _ in deltas:
        bad = sum(d[1] for d in deltas if t - window_s < d[0] <= t)
        total = sum(d[2] for d in deltas if t - window_s < d[0] <= t)
        ratio = bad / total if total else 0.0
        out.append((t, ratio / budget))
    return out


def render_timeline(timeline, page_burn=2.0, width=64):
    """One burn timeline as an ASCII strip (resampled to ``width``
    columns; ``!`` marks samples at or past the page threshold)."""
    if not timeline:
        return "(no samples)"
    n = len(timeline)
    cols = []
    for c in range(min(width, n)):
        lo = c * n // min(width, n)
        hi = max(lo + 1, (c + 1) * n // min(width, n))
        burn = max(b for (_, b) in timeline[lo:hi])
        if burn >= page_burn:
            cols.append("!")
        else:
            frac = min(1.0, burn / page_burn if page_burn else 0.0)
            cols.append(GLYPHS[int(frac * (len(GLYPHS) - 1))])
    peak = max(b for (_, b) in timeline)
    return "[%s] peak %.2fx over %.1fs" % (
        "".join(cols), peak, timeline[-1][0] - timeline[0][0])


def default_objectives():
    """The stock objective dicts (mirrors
    ``SLOMonitor.default_objectives`` without importing jax-adjacent
    serving modules at tool load)."""
    return [
        {"name": "availability", "kind": "availability",
         "target": 0.999},
        {"name": "ttft", "kind": "latency", "series": "ttft",
         "threshold_s": 1.0, "target": 0.95},
        {"name": "decode_step", "kind": "latency",
         "series": "decode_step", "threshold_s": 0.25,
         "target": 0.99},
        {"name": "shed", "kind": "shed_rate", "target": 0.99},
    ]


def summary_record(results):
    """(record, exit_code) in the bench.py shape — one selection rule:
    paging-objective count once verdicts exist, series count while
    only the timeseries parsed."""
    verdicts = results.get("verdicts")
    if verdicts is not None:
        paging = sum(1 for v in verdicts if v.get("state_name")
                     == "page")
        return {
            "metric": "slo_objectives_paging",
            "value": paging,
            "unit": "objectives",
            "vs_baseline": 0,
            "configs": results,
        }, 0
    if results.get("series") is not None:
        return {
            "metric": "timeseries_series_parsed",
            "value": results["series"],
            "unit": "series",
            "vs_baseline": None,
            "configs": results,
        }, 0
    return {"metric": "slo_report_empty", "value": None,
            "unit": None, "vs_baseline": None, "configs": results}, 1


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("timeseries", help="captured "
                        "/timeseries.json payload")
    parser.add_argument("--slo", default=None, metavar="FILE",
                        help="captured /slo.json payload: adds the "
                             "monitor's own verdicts and uses its "
                             "objectives/windows for the timelines")
    parser.add_argument("--window", type=float, default=None,
                        metavar="S",
                        help="burn-rate window for the timelines "
                             "(default: the slo.json short window, "
                             "else 60)")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the final summary record "
                             "here")
    args = parser.parse_args(argv)

    ts = load_json(args.timeseries)
    results = {"timeseries": args.timeseries,
               "sampled_at": ts.get("sampled_at"),
               "series": len(ts.get("series", {})),
               "samples": ts.get("samples")}
    print(json.dumps(summary_record(results)[0]), flush=True)

    slo = load_json(args.slo) if args.slo else None
    page_burn = (slo or {}).get("page_burn", 2.0)
    objectives = default_objectives()
    if slo and slo.get("objectives"):
        seen, objectives = set(), []
        for row in slo["objectives"]:
            if row["objective"] in seen:
                continue
            seen.add(row["objective"])
            obj = {"name": row["objective"], "kind": row["kind"],
                   "target": row["target"]}
            if "threshold_s" in row:
                obj["threshold_s"] = row["threshold_s"]
                # the monitor round-trips the series name; fall back
                # to a name match only for older captures
                obj["series"] = row.get(
                    "series",
                    row["objective"] if row["objective"] in
                    ("ttft", "decode_step") else "ttft")
            objectives.append(obj)
    window_s = args.window or (slo or {}).get(
        "windows_s", [60.0])[0]

    # ---- burn timelines, one strip per (source, objective)
    timelines = 0
    for source in _sources(ts):
        for obj in objectives:
            tl = burn_timeline(ts, source, obj, window_s)
            if not tl:
                continue
            timelines += 1
            print("%-24s %-14s %s"
                  % (source, obj["name"],
                     render_timeline(tl, page_burn)),
                  file=sys.stderr)
    results["timelines"] = timelines
    results["window_s"] = window_s

    # ---- verdicts from the monitor's own snapshot
    if slo is not None:
        verdicts = []
        print("\n%-6s %-24s %-14s %-8s %s"
              % ("STATE", "source", "objective", "target", "burns"),
              file=sys.stderr)
        for row in slo.get("objectives", []):
            burns = " ".join(
                "%gs=%.2fx" % (b["window_s"], b["burn"])
                for b in row.get("burn_rates", []))
            print("%-6s %-24s %-14s %-8g %s"
                  % (row["state_name"].upper(), row["source"],
                     row["objective"], row["target"], burns),
                  file=sys.stderr)
            verdicts.append({"source": row["source"],
                             "objective": row["objective"],
                             "state_name": row["state_name"],
                             "burn_rates": row.get("burn_rates", [])})
        results["verdicts"] = verdicts
        results["worst_state"] = slo.get("worst_state_name")

    record, rc = summary_record(results)
    line = json.dumps(record)
    print(line)                  # final full record — last line wins
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
