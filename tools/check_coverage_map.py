"""Validate docs/COVERAGE.md: every cited file and test symbol exists.

The coverage map is the judge-facing inventory (SURVEY §2 → implementation
→ tests); a row pointing at a renamed file or test silently breaks its
claim.  Run directly or via tests/test_docs.py.

Exit 0 when every citation resolves; prints offenders and exits 1
otherwise.
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def check(text):
    """Returns a list of problem strings (empty = clean)."""
    problems = []
    cited_files = set(re.findall(
        r"`((?:veles_tpu|tests|tools)/[\w/.]+\.(?:py|cpp))`", text))
    for rel in sorted(cited_files):
        if not (REPO / rel).exists():
            problems.append("missing file: %s" % rel)
    # package-relative citations like `ops/moe.py`
    for rel in sorted(set(re.findall(
            r"`((?:ops|loader|parallel|samples|native)/[\w/.]+\.(?:py|cpp))`",
            text))):
        if not (REPO / "veles_tpu" / rel).exists():
            problems.append("missing file: veles_tpu/%s" % rel)
    # `tests/test_x.py::symbol` references must name real symbols
    for rel, symbol in sorted(set(re.findall(
            r"`(tests/test_\w+\.py)::(\w+)`", text))):
        path = REPO / rel
        if not path.exists():
            problems.append("missing test file: %s" % rel)
        elif symbol not in path.read_text():
            problems.append("missing symbol: %s::%s" % (rel, symbol))
    return problems


def main():
    text = (REPO / "docs" / "COVERAGE.md").read_text()
    problems = check(text)
    for p in problems:
        print(p)
    print("%d citations problems" % len(problems))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
