"""Chaos bench (ISSUE 10): the serving resilience layer under
deterministic injected faults.

Seven scenarios, each driven by a seeded
``veles_tpu/serving/faults.py::FaultPlan`` so a given run always
injects at the same dispatches:

- ``kill_one_replica_under_load`` — replica 0's worker FREEZES
  mid-traffic (the wedged-device shape).  The health checker's
  staleness watch quarantines it through the router's drain path,
  drained work re-places (wedged mid-decode lanes force-replace after
  the drain timeout), and EVERY admitted request completes exactly
  once with output bit-identical to ``transformer.generate`` — no
  loss, no duplicate, no wedge.
- ``slow_replica_tail`` — replica 0 pays an injected per-dispatch
  latency spike.  The same workload runs hedging OFF then ON:
  requests outstanding past the hedge threshold duplicate onto the
  fast replica, first complete wins (parity unchanged), and the
  record carries both latency distributions plus the
  ``requests_hedged`` / ``hedge_wins`` evidence.
- ``pool_exhaustion_storm`` — a page-pool flood (many concurrent
  mixed-length requests against a tiny pool) plus injected admission
  storms.  Every request either completes exactly greedy or sheds as
  429/PoolExhausted/503 — never another error class, never a hang —
  and afterwards the pool drains back to FULL with allocator
  invariants re-verified (leak-freedom).
- ``weight_swap_under_load`` — requests straddle a canary-first
  ``Router.deploy`` (ISSUE 11): all complete exactly once with zero
  5xx, every delivered row is bit-identical to the weights version
  its reply is stamped with (pre-swap → old, post-swap → new), and an
  injected bad canary (``engine.swap`` fault) auto-rolls back with no
  client-visible errors.
- ``traced_flight_recorder`` — requests run TRACED (ISSUE 12) under
  injected chunk faults: a retried request's trace shows both
  attempts (the errored one included), every retained span tree
  verifies (one root, no orphans, no unclosed spans), the faulted
  request's timeline reconstructs from the flight-recorder ring
  after the fact, and its waterfall was auto-dumped the moment it
  failed.
- ``slo_burn_alert`` (ISSUE 14) — a fault-slowed replica burns its
  decode-step latency SLO: the telemetry store samples both replicas,
  the SLO monitor's burn-rate state machine reaches PAGE on the slow
  one, and within TWO sampling windows the page signal walks the
  health checker (``note_slo_page``) to quarantine through the
  router's drain path — in-flight work re-places on the survivor and
  every request completes exactly once, bit-identical to greedy.
- ``fault_free_overhead`` — the acceptance leg for "unarmed is
  free": measures the per-call cost of an UNARMED fault hook, an
  UNARMED trace site (ISSUE 12) and the health checker's per-scan
  cost, expresses them as a fraction of a measured decode step, and
  asserts the sum < 2% (armed tracing's span cost is recorded for
  PERF.md, not bounded).  The ISSUE 14 telemetry bound rides here
  too: the ARMED sampler (one ``sample_once()`` amortized over its
  interval) plus the tracer's per-dispatch incremental-ledger update
  are measured and asserted < 1% of a decode step.

A bench.py-style summary JSON line streams after EVERY completed
scenario (last-line-wins under an outer watchdog kill), and the final
line carries the full record.

Standalone (CPU is fine — every scenario is about control flow, not
device speed)::

    python tools/chaos_bench.py [--smoke] [--json out.json]

``tools/chaos_smoke.py`` runs the tier-1 subset (one scenario, tiny
model, <60s) — the CI guard that keeps this plumbing from rotting
between TPU sessions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from lm_bench import (build_params, expected_rows,  # noqa: E402
                      mixed_length_prompts)
from load_gen import _percentile  # noqa: E402 — the ONE quantile helper


def _lat_summary(lats):
    lats = sorted(lats)
    return {"mean": round(sum(lats) / len(lats), 4) if lats else 0.0,
            "p50": round(_percentile(lats, 0.50), 4),
            "p95": round(_percentile(lats, 0.95), 4),
            "p99": round(_percentile(lats, 0.99), 4),
            "max": round(lats[-1], 4) if lats else 0.0}


def _build_replicas(params, n_heads, max_len, n, slots, plans,
                    tag="chaos", **engine_kw):
    """N single-device replicas; ``plans[i]`` (or None) arms replica
    i's fault sites."""
    from veles_tpu.serving import LMEngine, ServingMetrics
    return [LMEngine(params, n_heads=n_heads, max_len=max_len,
                     slots=slots, name="%s_r%d" % (tag, i),
                     metrics=ServingMetrics(
                         tag, labels={"replica": str(i)}),
                     faults=plans[i], **engine_kw)
            for i in range(n)]


def _submit_all(server, prompts, n_new, deadline_s=120.0):
    """Closed-loop admission: back off on 429s so a storm measures
    shedding, not a crashed client."""
    from veles_tpu.serving import Overloaded
    futures = []
    stop = time.monotonic() + deadline_s
    for p in prompts:
        while True:
            try:
                futures.append(server.submit(p, n_new))
                break
            except Overloaded as e:
                if time.monotonic() > stop:
                    raise
                time.sleep(min(getattr(e, "retry_after", 0.05), 0.1))
    return futures


# --------------------------------------------------------------- scenarios
def scenario_kill_replica(params, n_heads, max_len, prompts, n_new,
                          expect, slots=2, freeze_after_ticks=6,
                          drain_timeout_s=0.5):
    """Kill-one-replica-under-load: see the module docstring."""
    from veles_tpu.serving import FaultPlan, HealthChecker, Router
    plan = FaultPlan(seed=0)
    # CHUNKED prefill: every program is warmed at start, so the
    # staleness watch sees only real wedges — a lazily-compiled prompt
    # bucket would stall the progress counters exactly like a freeze
    # (the stall_s sizing rule the HealthChecker docstring documents)
    replicas = _build_replicas(params, n_heads, max_len, 2, slots,
                               [plan, None], tag="chaos_kill",
                               prefill_chunk=16)
    router = Router(replicas, retries=2,
                    drain_timeout_s=drain_timeout_s)
    checker = HealthChecker(router, interval_s=0.05,
                            probe_timeout_s=2.0, fail_threshold=2,
                            cooldown_s=600.0, stall_s=0.3)
    router.start()
    plan.arm("engine.tick", kind="freeze",
             after=plan.calls("engine.tick") + freeze_after_ticks,
             duration_s=600.0)
    t0 = time.monotonic()
    try:
        futures = _submit_all(router, prompts, n_new)
        # drive the health state machine synchronously until the wedge
        # is detected and every request resolved (deterministic: the
        # freeze always fires at the same tick)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            checker.step()
            if all(f.done() for f in futures):
                break
            time.sleep(0.05)
        completed = 0
        for p, f, exp in zip(prompts, futures, expect):
            out = f.result(timeout=60)     # raises on any failure
            if len(out) != n_new:
                raise AssertionError("partial result delivered: %d/%d"
                                     % (len(out), n_new))
            if not numpy.array_equal(numpy.concatenate([p, out]), exp):
                raise AssertionError(
                    "post-fault output diverged from greedy generate "
                    "for prompt of length %d" % len(p))
            completed += 1
        m = router.metrics
        quarantined = not router._live[0]
        record = {
            "scenario": "kill_one_replica_under_load",
            "requests": len(prompts),
            "completed_exactly_once": completed,
            "parity_vs_generate": True,
            "replica0_quarantined": quarantined,
            "circuit_open_total": m.counter("circuit_open_total"),
            "requeued_requests": m.counter("requeued_requests"),
            "requests_retried": m.counter("requests_retried"),
            "drain_forced_replacements":
                m.counter("drain_forced_replacements"),
            "freeze_fired": plan.fired("engine.tick"),
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if not quarantined:
            raise AssertionError("health checker never quarantined the "
                                 "frozen replica")
        if completed != len(prompts):
            raise AssertionError("%d/%d requests completed"
                                 % (completed, len(prompts)))
        return record
    finally:
        plan.release()
        checker.stop()
        router.stop()


def scenario_slow_replica(params, n_heads, max_len, prompts, n_new,
                          expect, slots=2, spike_s=0.15,
                          hedge_after_s=0.25):
    """Slow-replica tail: the same workload with hedging off then on;
    hedging must fire, win, and keep parity."""
    from veles_tpu.serving import FaultPlan, Router

    def run(hedge):
        plan = FaultPlan(seed=0).arm("engine.step", kind="latency",
                                     latency_s=spike_s)
        replicas = _build_replicas(params, n_heads, max_len, 2, slots,
                                   [plan, None], tag="chaos_slow",
                                   prefill_chunk=16)
        router = Router(replicas,
                        hedge_after_s=hedge_after_s if hedge else 0.0)
        router.start()
        try:
            lats = []
            futures = _submit_all(router, prompts, n_new)
            t_sub = {id(f): time.monotonic() for f in futures}
            for p, f, exp in zip(prompts, futures, expect):
                out = f.result(timeout=120)
                lats.append(time.monotonic() - t_sub[id(f)])
                if not numpy.array_equal(
                        numpy.concatenate([p, out]), exp):
                    raise AssertionError(
                        "hedged output diverged from greedy generate")
            m = router.metrics
            return {"latency_s": _lat_summary(lats),
                    "requests_hedged": m.counter("requests_hedged"),
                    "hedge_wins": m.counter("hedge_wins")}
        finally:
            plan.release()
            router.stop()

    base = run(hedge=False)
    hedged = run(hedge=True)
    if not hedged["requests_hedged"]:
        raise AssertionError("hedging never fired on the slow replica")
    return {
        "scenario": "slow_replica_tail",
        "requests": len(prompts),
        "parity_vs_generate": True,
        "injected_step_spike_s": spike_s,
        "hedge_after_s": hedge_after_s,
        "no_hedge": base,
        "hedge": hedged,
        "p99_ratio_hedge_vs_none": (
            round(hedged["latency_s"]["p99"]
                  / base["latency_s"]["p99"], 3)
            if base["latency_s"]["p99"] else None),
    }


def scenario_pool_storm(params, n_heads, max_len, prompts, n_new,
                        expect, slots=2, pool_pages=6, chunk=8,
                        deadline_s=2.0):
    """Pool-exhaustion storm: shed (429/503), never errored, never
    wedged; pool drains leak-free afterwards."""
    from veles_tpu.serving import (DeadlineExceeded, FaultPlan,
                                  LMEngine, Overloaded, ServingMetrics)
    # the pool must be able to place the LARGEST single request (an
    # up-front 400 otherwise) while staying far below the aggregate
    # demand — that gap IS the storm
    need = max(-(-(len(p) + n_new) // chunk) for p in prompts)
    pool_pages = max(pool_pages, need + 1)
    # the storm site: every 7th admission also 429s by injection, on
    # top of the natural pool pressure
    plan = FaultPlan(seed=0).arm("engine.submit", kind="error",
                                 exc="PoolExhausted", every=7)
    engine = LMEngine(params, n_heads=n_heads, max_len=max_len,
                      slots=slots, paged_kv=pool_pages,
                      prefill_chunk=chunk, deadline_s=deadline_s,
                      queue_depth=len(prompts) + 8,
                      name="chaos_pool",
                      metrics=ServingMetrics("chaos_pool"),
                      faults=plan).start()
    t0 = time.monotonic()
    try:
        outcomes = {"ok": 0, "rejected_429": 0, "shed_503": 0}
        futures = []
        for p in prompts:
            try:
                futures.append((p, engine.submit(p, n_new)))
            except Overloaded:
                outcomes["rejected_429"] += 1
        for p, f in futures:
            try:
                out = f.result(timeout=120)
                exp = expect[[i for i, q in enumerate(prompts)
                              if q is p][0]]
                if not numpy.array_equal(
                        numpy.concatenate([p, out]), exp):
                    raise AssertionError(
                        "storm survivor diverged from greedy generate")
                outcomes["ok"] += 1
            except DeadlineExceeded:
                outcomes["shed_503"] += 1
            except Overloaded:
                outcomes["rejected_429"] += 1
            # any OTHER exception propagates: the storm must shed, not
            # error — the scenario fails loudly on a 500-class fault
        while engine._trie is not None and engine._trie.evict_one():
            pass
        invariants = engine.verify_pool_invariants()
        if engine._pool.free_pages != engine._pool.num_pages:
            raise AssertionError(
                "pool leaked %d page(s) after the storm"
                % (engine._pool.num_pages - engine._pool.free_pages))
        total = sum(outcomes.values())
        if total != len(prompts):
            raise AssertionError("accounted %d of %d requests"
                                 % (total, len(prompts)))
        return {
            "scenario": "pool_exhaustion_storm",
            "requests": len(prompts),
            "pool_pages": pool_pages,
            "outcomes": outcomes,
            "shed_not_errored": True,       # else we raised above
            "injected_admission_storms": plan.fired("engine.submit"),
            "pool_leak_free": True,
            "allocator_invariants": invariants,
            "wall_s": round(time.monotonic() - t0, 3),
        }
    finally:
        engine.stop()


def scenario_traced_flight_recorder(params, n_heads, max_len, prompts,
                                    n_new, expect, slots=2):
    """Traced serving under injected faults (ISSUE 12): the flight
    recorder must reproduce a faulted request's timeline AFTER the
    fact, auto-dump it the moment it fails, and keep every retained
    span tree sound (one root, no orphans, no unclosed spans) while
    parity holds for the survivors.

    Two sub-legs: (a) a 2-replica ROUTER with retries — a request whose
    first attempt dies on the faulted replica completes on the second,
    and its trace shows BOTH attempts (the errored one included); (b) a
    single engine with a recurring chunk fault and no retry — the
    failed requests' traces land in the 'errors'-mode ring exactly,
    each auto-dumped as waterfall text."""
    from veles_tpu.serving import (FaultPlan, LMEngine, Router,
                                   ServingMetrics, SpanTracer,
                                   cost_ledger, format_waterfall,
                                   verify_integrity)

    # ---- (a) routed retry: the errored attempt stays in the timeline
    plan = FaultPlan(seed=0).arm("engine.chunk", kind="error",
                                 calls={2})
    tracer = SpanTracer(mode="all", last=4 * len(prompts) + 16)
    replicas = _build_replicas(params, n_heads, max_len, 2, slots,
                               [plan, None], tag="chaos_trace",
                               prefill_chunk=16, tracer=tracer)
    router = Router(replicas, retries=2, tracer=tracer)
    router.start()
    t0 = time.monotonic()
    try:
        futures = _submit_all(router, prompts, n_new)
        for p, f, exp in zip(prompts, futures, expect):
            out = f.result(timeout=120)
            if not numpy.array_equal(numpy.concatenate([p, out]), exp):
                raise AssertionError(
                    "traced+faulted output diverged from greedy "
                    "generate")
    finally:
        plan.release()
        router.stop()
    recs = tracer.requests()
    integrity = verify_integrity(recs)      # raises on a broken tree
    retried = [r for r in recs
               if sum(1 for s in r["spans"]
                      if s["name"] == "attempt") > 1]
    if not retried:
        raise AssertionError("no request shows a second attempt after "
                             "the injected chunk fault")
    errored_attempts = [
        s for r in retried for s in r["spans"]
        if s["name"] == "attempt" and "error" in s["attrs"]]
    if not errored_attempts:
        raise AssertionError("the retried request's first attempt did "
                             "not record its error")
    ledger = cost_ledger(recs)
    if not ledger:
        raise AssertionError("traced run produced an empty cost ledger")

    # ---- (b) flight recorder: errors-only retention + auto-dump
    plan_b = FaultPlan(seed=0).arm("engine.chunk", kind="error",
                                   every=3)
    rec_tracer = SpanTracer(mode="errors", last=16)
    engine = LMEngine(params, n_heads=n_heads, max_len=max_len,
                      slots=slots, prefill_chunk=16,
                      name="chaos_recorder",
                      metrics=ServingMetrics("chaos_recorder"),
                      faults=plan_b, tracer=rec_tracer).start()
    try:
        futures = [(p, engine.submit(p, n_new)) for p in prompts]
        failed, ok = [], 0
        for i, (p, f) in enumerate(futures):
            try:
                out = f.result(timeout=120)
            except Exception:   # noqa: BLE001 — the injected fault
                failed.append((p, f))
                continue
            if not numpy.array_equal(numpy.concatenate([p, out]),
                                     expect[i]):
                raise AssertionError(
                    "survivor diverged from greedy generate beside "
                    "injected faults")
            ok += 1
        if not failed:
            raise AssertionError("the every=3 chunk fault never fired")
    finally:
        plan_b.release()
        engine.stop()
    # reconstruction AFTER the fact: the failed request's rid pulls its
    # full timeline out of the ring, and the auto-dump already fired
    rid = failed[0][1].request.trace.rid
    rec = rec_tracer.find(rid)
    if rec is None:
        raise AssertionError("faulted request %s not in the flight "
                             "recorder ring" % rid)
    if not rec["error"] or "InjectedFault" not in rec["error"]:
        raise AssertionError("recorded error %r does not name the "
                             "injected fault" % (rec["error"],))
    fault_spans = [s for s in rec["spans"]
                   if "error" in s["attrs"]
                   and s["name"] == "prefill.chunk"]
    if not fault_spans:
        raise AssertionError("the faulted dispatch is missing from "
                             "the reconstructed timeline")
    waterfall = format_waterfall(rec)
    if "InjectedFault" not in waterfall:
        raise AssertionError("waterfall does not show the fault")
    dump_rids = {d["rid"] for d in rec_tracer.dumps()}
    if rid not in dump_rids:
        raise AssertionError("faulted request %s was not auto-dumped"
                             % rid)
    retained = rec_tracer.requests()
    verify_integrity(retained)
    if len(retained) != len(failed):
        raise AssertionError(
            "'errors' mode retained %d records for %d failed requests"
            % (len(retained), len(failed)))
    return {
        "scenario": "traced_flight_recorder",
        "requests": 2 * len(prompts),
        "parity_vs_generate": True,
        "span_integrity": integrity,
        "retried_request_attempts": max(
            sum(1 for s in r["spans"] if s["name"] == "attempt")
            for r in retried),
        "ledger_rows": len(ledger),
        "ledger_dispatches": int(sum(r["dispatches"] for r in ledger)),
        "faulted_requests": len(failed),
        "recorder_retained": len(retained),
        "auto_dumps": len(dump_rids),
        "fault_timeline_reconstructed": True,
        "wall_s": round(time.monotonic() - t0, 3),
    }


def scenario_overhead(params, n_heads, max_len, prompts, n_new,
                      slots=2, hook_calls=200000):
    """Fault-free overhead: the UNARMED fault layer, the UNARMED
    tracing layer (ISSUE 12) and the health prober must together cost
    <2% of a decode step (the acceptance bound).

    Measured facts: (a) the per-call cost of an unarmed fault hook
    (one attribute-is-None check — timed over ``hook_calls``
    iterations) scaled by the hooks a decode tick crosses; (b) the
    unarmed TRACE site — literally ``engine._tracer is None`` —
    scaled the same way; (c) the health checker's per-scan cost on a
    BUSY fleet (counter reads, no probe) amortized over its interval.
    All expressed against a decode-step wall measured live on this
    host.  ARMED tracing cost (span begin/end pair, scaled to the
    spans a traced tick records) is measured and RECORDED for the
    PERF.md armed-vs-unarmed row, but not bounded — arming the tracer
    buys the fence + record cost knowingly."""
    from veles_tpu.serving import HealthChecker, LMEngine, Router, \
        ServingMetrics, SpanTracer
    engine = LMEngine(params, n_heads=n_heads, max_len=max_len,
                      slots=slots, name="chaos_ovh",
                      metrics=ServingMetrics("chaos_ovh")).start()
    router = Router([engine])
    checker = HealthChecker(router, interval_s=1.0)
    try:
        # a real decode-step wall from live traffic (warm programs)
        futures = [engine.submit(p, n_new) for p in prompts]
        for f in futures:
            f.result(timeout=120)
        step_s = engine.metrics.ewma("decode_step") or 1e-4
        # (a) the unarmed hook, exactly as compiled into the engine
        t0 = time.perf_counter()
        for _ in range(hook_calls):
            engine._fault("engine.step")
        hook_s = (time.perf_counter() - t0) / hook_calls
        # a decode tick crosses 2 sites (engine.tick + engine.step);
        # admission-path sites are per REQUEST, not per token — charge
        # them too, conservatively, as one more per tick
        hooks_per_tick = 3
        hook_frac = hooks_per_tick * hook_s / step_s
        # (b) the unarmed TRACE sites (ISSUE 12) — the literal check
        # every site compiles down to; a traced tick crosses the step
        # site, the per-lane ctx reads and the fence guard — charge 4
        t0 = time.perf_counter()
        for _ in range(hook_calls):
            if engine._tracer is not None:
                raise AssertionError("tracer must be unarmed here")
        trace_site_s = (time.perf_counter() - t0) / hook_calls
        trace_sites_per_tick = 4
        trace_frac = trace_sites_per_tick * trace_site_s / step_s
        # (b2) the UNARMED lock-order shim (ISSUE 15): serving locks
        # are lockcheck wrappers whose unarmed acquire/release adds a
        # module-global None-check over the raw primitive — measured
        # as the DELTA of a with-block round trip, scaled by the lock
        # acquisitions a decode tick crosses (queue pop + gauge
        # updates + metrics records, ~8 conservatively)
        import threading as _threading
        from veles_tpu.serving import lockcheck
        shim_cond = lockcheck.make_condition("chaos_ovh.shim")
        raw_cond = _threading.Condition()
        pairs0 = 50000
        t0 = time.perf_counter()
        for _ in range(pairs0):
            with shim_cond:
                pass
        shim_pair_s = (time.perf_counter() - t0) / pairs0
        t0 = time.perf_counter()
        for _ in range(pairs0):
            with raw_cond:
                pass
        raw_pair_s = (time.perf_counter() - t0) / pairs0
        lock_shim_s = max(0.0, shim_pair_s - raw_pair_s)
        lock_acquires_per_tick = 8
        lock_frac = lock_acquires_per_tick * lock_shim_s / step_s
        # ARMED tracing: one begin/end span pair, scaled to a traced
        # tick's records (batch lanes + bookkeeping) — recorded for
        # the PERF.md armed row, not part of the unarmed bound
        pairs = 20000
        tr = SpanTracer(mode="all", last=4, max_spans=2 * pairs + 16)
        ctx = tr.start_request(name="overhead", cat="bench")
        t0 = time.perf_counter()
        for _ in range(pairs):
            tr.end(tr.begin(ctx, "decode.step", cat="decode"))
        span_pair_s = (time.perf_counter() - t0) / pairs
        tr.finish_request(ctx)
        armed_spans_per_tick = slots + 2
        armed_frac = armed_spans_per_tick * span_pair_s / step_s
        # (c) one health scan over a busy replica (staleness math
        # only: the engine has queued work during the scan)
        fut = engine.submit(prompts[0], max(8, n_new))
        t0 = time.perf_counter()
        scans = 50
        for _ in range(scans):
            checker.step()
        scan_s = (time.perf_counter() - t0) / scans
        fut.result(timeout=120)
        # the prober runs once per interval_s of wall time, whatever
        # the decode rate — its amortized cost is simply the fraction
        # of wall clock a scan occupies
        health_frac = scan_s / checker.interval_s
        overhead = hook_frac + trace_frac + lock_frac + health_frac
        # ---- ISSUE 14: the ARMED continuous-telemetry bound.  (a)
        # the sampler: one full sample_once() — runtime probes +
        # source snapshots + ring folds — amortized over its
        # interval_s of wall clock, exactly like the health scan;
        # (b) the tracer's incremental cost-ledger update, paid once
        # per device dispatch on the armed path — together they must
        # stay under 1% of a decode step
        from veles_tpu.serving import telemetry_for
        store = telemetry_for(router, interval_s=1.0)
        store.sample_once()          # warm the probes' first pass
        t0 = time.perf_counter()
        samples = 20
        for _ in range(samples):
            store.sample_once()
        sample_s = (time.perf_counter() - t0) / samples
        sampler_frac = sample_s / store.interval_s
        ledger_tr = SpanTracer(mode="all", last=4)
        ledger_attrs = {"batch": slots, "bucket": slots,
                        "backend": "xla"}
        t0 = time.perf_counter()
        notes = 50000
        with ledger_tr._lock:
            for _ in range(notes):
                ledger_tr._ledger_note("decode.step", ledger_attrs,
                                       0.0, 0.001, slots)
        ledger_note_s = (time.perf_counter() - t0) / notes
        ledger_frac = ledger_note_s / step_s
        telemetry_frac = sampler_frac + ledger_frac
        record = {
            "scenario": "fault_free_overhead",
            "decode_step_ewma_s": round(step_s, 6),
            "unarmed_hook_ns": round(hook_s * 1e9, 1),
            "hooks_per_decode_tick": hooks_per_tick,
            "hook_frac_of_decode_step": round(hook_frac, 6),
            # ISSUE 12: the tracing layer's three rows — unarmed site
            # (bounded), armed span pair (recorded; arming also buys
            # the block_until_ready fence, which is the dispatch
            # itself, not overhead)
            "unarmed_trace_site_ns": round(trace_site_s * 1e9, 1),
            "trace_sites_per_tick": trace_sites_per_tick,
            "trace_frac_of_decode_step": round(trace_frac, 6),
            "armed_span_pair_ns": round(span_pair_s * 1e9, 1),
            "armed_spans_per_tick": armed_spans_per_tick,
            "armed_trace_frac_of_decode_step": round(armed_frac, 6),
            # ISSUE 15: the unarmed lock-order witness shim's rows —
            # folded into overhead_frac, same 2% bound
            "lock_shim_pair_ns": round(shim_pair_s * 1e9, 1),
            "raw_lock_pair_ns": round(raw_pair_s * 1e9, 1),
            "lock_shim_delta_ns": round(lock_shim_s * 1e9, 1),
            "lock_acquires_per_tick": lock_acquires_per_tick,
            "lock_shim_frac_of_decode_step": round(lock_frac, 6),
            "health_scan_s": round(scan_s, 6),
            "health_scan_interval_s": checker.interval_s,
            "health_frac_of_decode_step": round(health_frac, 6),
            "overhead_frac": round(overhead, 6),
            "bound": 0.02,
            # ISSUE 14: the armed-telemetry rows and their own bound
            "telemetry_sample_s": round(sample_s, 6),
            "telemetry_interval_s": store.interval_s,
            "sampler_frac_of_decode_step": round(sampler_frac, 6),
            "ledger_note_ns": round(ledger_note_s * 1e9, 1),
            "ledger_frac_of_decode_step": round(ledger_frac, 6),
            "telemetry_frac": round(telemetry_frac, 6),
            "telemetry_bound": 0.01,
        }
        if overhead >= 0.02:
            raise AssertionError(
                "unarmed fault layer + unarmed tracing + unarmed "
                "lock shim + health prober cost %.3f%% of a decode "
                "step (bound: 2%%)" % (100 * overhead))
        if telemetry_frac >= 0.01:
            raise AssertionError(
                "armed telemetry sampler + incremental ledger cost "
                "%.3f%% of a decode step (bound: 1%%)"
                % (100 * telemetry_frac))
        return record
    finally:
        checker.stop()
        router.stop()


def scenario_weight_swap(params_old, params_new, n_heads, max_len,
                         prompts, n_new, expect_old, expect_new,
                         slots=2):
    """Weight-swap-under-load (ISSUE 11): N requests STRADDLE a
    canary-first ``Router.deploy`` — every request completes exactly
    once with zero 5xx, each delivered row is bit-identical to the
    weights version its reply is stamped with (pre-swap rows → old
    weights, post-swap rows → new), and an injected BAD canary
    (``engine.swap`` fault) auto-rolls back with no client-visible
    errors."""
    from veles_tpu.serving import FaultPlan, Router
    plan = FaultPlan(seed=0)        # replica 0: armed for the BAD deploy
    replicas = _build_replicas(params_old, n_heads, max_len, 2, slots,
                               [plan, None], tag="chaos_swap",
                               prefill_chunk=16)
    router = Router(replicas)
    router.start()
    t0 = time.monotonic()
    try:
        # ---- phase 1: a GOOD deploy with requests in flight
        futures = _submit_all(router, prompts, n_new)
        rec1 = router.deploy(params_new, version=1, canary=1,
                             canary_fraction=0.5, watch_s=0.0)
        if rec1["rolled_back"] or not rec1["completed"]:
            raise AssertionError("good deploy did not complete: %r"
                                 % rec1)
        # post-swap wave: every row must decode on the NEW weights
        futures2 = _submit_all(router, prompts, n_new)
        versions_seen = {}
        completed = 0
        for wave, fleet_version in ((futures, None), (futures2, 1)):
            for p, f in zip(prompts, wave):
                out = f.result(timeout=120)   # raises on ANY failure
                if len(out) != n_new:
                    raise AssertionError(
                        "partial result delivered: %d/%d"
                        % (len(out), n_new))
                ver = f.job.version
                if fleet_version is not None and ver != fleet_version:
                    raise AssertionError(
                        "post-swap row stamped v%s, fleet is v%s"
                        % (ver, fleet_version))
                idx = [i for i, q in enumerate(prompts) if q is p][0]
                exp = (expect_old if ver == 0 else expect_new)[idx]
                if not numpy.array_equal(
                        numpy.concatenate([p, out]), exp):
                    raise AssertionError(
                        "row stamped v%s is not bit-identical to that "
                        "version's greedy generate" % ver)
                versions_seen[ver] = versions_seen.get(ver, 0) + 1
                completed += 1
        # ---- phase 2: injected BAD canary — the swap apply faults
        plan.arm("engine.swap", kind="error",
                 calls={plan.calls("engine.swap") + 1})
        futures3 = _submit_all(router, prompts, n_new)
        rec2 = router.deploy(params_old, version=2, canary=1,
                             canary_fraction=0.5, watch_s=0.0)
        if not rec2["rolled_back"]:
            raise AssertionError("bad canary did not roll back: %r"
                                 % rec2)
        for p, f in zip(prompts, futures3):
            out = f.result(timeout=120)       # no client-visible errors
            if len(out) != n_new:
                raise AssertionError("partial result after rollback")
            idx = [i for i, q in enumerate(prompts) if q is p][0]
            if not numpy.array_equal(numpy.concatenate([p, out]),
                                     expect_new[idx]):
                raise AssertionError(
                    "post-rollback row diverged from the serving (v1) "
                    "weights")
            completed += 1
        m = router.metrics
        for i, e in enumerate(replicas):
            if e.weights_version != 1:
                raise AssertionError(
                    "replica %d serves v%s after the rollback (fleet "
                    "must still be v1)" % (i, e.weights_version))
        snap = m.snapshot()
        record = {
            "scenario": "weight_swap_under_load",
            "requests": 3 * len(prompts),
            "completed_exactly_once": completed,
            "zero_5xx": True,               # else we raised above
            "versions_observed": {str(k): v for k, v
                                  in sorted(versions_seen.items())},
            "parity_per_stamped_version": True,
            "deploys_total": m.counter("deploys_total"),
            "rollbacks_total": m.counter("rollbacks_total"),
            "bad_canary_rolled_back": rec2["rolled_back"],
            "rollback_reason": rec2["reason"],
            "weights_version_gauges": {
                k: v for k, v in snap["gauges"].items()
                if k.startswith("weights_version")},
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if m.counter("rollbacks_total") != 1:
            raise AssertionError("expected exactly one rollback, saw %d"
                                 % m.counter("rollbacks_total"))
        if completed != 3 * len(prompts):
            raise AssertionError("%d/%d requests completed"
                                 % (completed, 3 * len(prompts)))
        return record
    finally:
        plan.release()
        router.stop()


def scenario_slo_burn_alert(params, n_heads, max_len, prompts, n_new,
                            expect, slots=2, spike_s=0.06):
    """SLO burn-rate alerting end to end (ISSUE 14): replica 0 pays an
    injected per-step latency spike, the telemetry store samples both
    replicas' metrics, the SLO monitor's decode-step objective burns
    to PAGE on replica 0 only, and the page signal must walk the
    health checker to quarantine WITHIN TWO SAMPLING WINDOWS — with
    in-flight work drained onto the survivor and every request
    completing exactly once, bit-identical to greedy."""
    from veles_tpu.serving import (FaultPlan, HealthChecker, Objective,
                                   Router, SLOMonitor, telemetry_for)
    from veles_tpu.serving.metrics import _registry_key
    plan = FaultPlan(seed=0).arm("engine.step", kind="latency",
                                 latency_s=spike_s)
    replicas = _build_replicas(params, n_heads, max_len, 2, slots,
                               [plan, None], tag="chaos_slo",
                               prefill_chunk=16)
    # round_robin: the placement baseline that KEEPS sending traffic
    # at the slow replica — exactly the regime burn alerting is for
    # (the metrics policy would route around it and hide the burn)
    router = Router(replicas, policy="round_robin")
    checker = HealthChecker(router, interval_s=600.0,
                            fail_threshold=2, cooldown_s=600.0)
    store = telemetry_for(router, interval_s=600.0)  # manual ticks
    monitor = SLOMonitor(
        store,
        [Objective("decode_step", "latency", 0.9,
                   series="decode_step", threshold_s=spike_s / 2)],
        windows_s=(30.0, 60.0), min_events=3, checker=checker,
        source_replicas={_registry_key(e.metrics): i
                         for i, e in enumerate(replicas)},
        metrics=router.metrics)
    store.add_listener(monitor.sample_once)
    router.start()
    t0 = time.monotonic()
    try:
        # baseline tick: rates and histogram deltas need a pre-fault
        # point; no events yet, so the monitor holds OK (min_events)
        store.sample_once()
        # wave 1 establishes the burn evidence in the rings
        futures = _submit_all(router, prompts, n_new)
        for f in futures:
            f.result(timeout=120)
        # wave 2 is IN FLIGHT while the page fires — the quarantine
        # must drain it onto the survivor, exactly once
        futures2 = _submit_all(router, prompts, n_new)
        windows = 0
        for _ in range(2):               # the acceptance bound
            store.sample_once()          # listener runs the monitor
            windows += 1
            if not router._live[0]:
                break
        quarantined = not router._live[0]
        completed = 0
        for wave in (futures, futures2):
            for p, f in zip(prompts, wave):
                out = f.result(timeout=120)   # raises on any failure
                if len(out) != n_new:
                    raise AssertionError(
                        "partial result delivered: %d/%d"
                        % (len(out), n_new))
                idx = [i for i, q in enumerate(prompts)
                       if q is p][0]
                if not numpy.array_equal(
                        numpy.concatenate([p, out]), expect[idx]):
                    raise AssertionError(
                        "post-quarantine output diverged from greedy "
                        "generate")
                completed += 1
        src0 = _registry_key(replicas[0].metrics)
        state0 = monitor.state(src0, "decode_step")
        src1 = _registry_key(replicas[1].metrics)
        state1 = monitor.state(src1, "decode_step")
        m = router.metrics
        record = {
            "scenario": "slo_burn_alert",
            "requests": 2 * len(prompts),
            "completed_exactly_once": completed,
            "parity_vs_generate": True,
            "injected_step_spike_s": spike_s,
            "slo_threshold_s": spike_s / 2,
            "sampling_windows_to_quarantine": windows,
            "replica0_slo_state": state0,
            "replica1_slo_state": state1,
            "replica0_quarantined": quarantined,
            "circuit_state": checker.states()[0],
            "slo_pages_total": m.counter("slo_pages_total"),
            "slo_page_signals": m.counter("slo_page_signals"),
            "requeued_requests": m.counter("requeued_requests"),
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if state0 != 2:
            raise AssertionError(
                "slow replica's objective never reached PAGE "
                "(state %d)" % state0)
        if state1 == 2:
            raise AssertionError(
                "healthy replica's objective paged too — the alert "
                "is not replica-scoped")
        if not quarantined:
            raise AssertionError(
                "burn-rate page did not reach the health checker "
                "within %d sampling windows" % windows)
        if checker.states()[0] != checker.OPEN:
            raise AssertionError(
                "health circuit is not OPEN after the SLO page")
        if completed != 2 * len(prompts):
            raise AssertionError("%d/%d requests completed"
                                 % (completed, 2 * len(prompts)))
        return record
    finally:
        plan.release()
        checker.stop()
        router.stop()
        store.stop()


def scenario_whilestep_fault(params, n_heads, max_len, prompts, n_new,
                             expect):
    """Mid-loop fault in the persistent while-megastep (ISSUE 19): a
    single ``engine.step`` fault fired inside the while-loop dispatch
    must fail EXACTLY the participants — the active lane AND the
    published standby-ring occupant riding the same program — with
    their pool pages home immediately, sound span trees for both, and
    bit-exact greedy parity for every survivor served afterwards
    through the same ring."""
    from veles_tpu.serving import (FaultPlan, InjectedFault, LMEngine,
                                   ServingMetrics, SpanTracer,
                                   verify_integrity)

    # max_len=64 / chunk=16 / slots=1 puts the DEFAULT paged pool at 4
    # pages — exactly one lane, zero ring headroom, so standby entries
    # would bounce forever on the all-or-nothing reservation.  Size the
    # pool for the lane plus both ring occupants explicitly.
    pool_pages = 3 * (max_len // 16)
    plan = FaultPlan(seed=0)                  # armed mid-flight below
    tracer = SpanTracer(mode="all", last=4 * len(prompts) + 16)
    engine = LMEngine(params, n_heads=n_heads, max_len=max_len,
                      slots=1, megastep=4, megastep_mode="while",
                      paged_kv=pool_pages, prefill_chunk=16,
                      refill_ring=2, faults=plan, tracer=tracer,
                      metrics=ServingMetrics("chaos_whilestep"),
                      name="chaos_whilestep").start()
    real = engine._whilestep_jit

    def slow(*a):
        # hold each megastep open long enough that the ring occupant
        # is published before the victim lane drains
        time.sleep(0.05)
        return real(*a)

    engine._whilestep_jit = slow
    t0 = time.monotonic()
    try:
        fa = engine.submit(prompts[0], max(n_new, 24))
        fb = engine.submit(prompts[1], n_new)
        deadline = time.monotonic() + 30.0
        while not any(e.ready for e in engine._ring):
            if time.monotonic() > deadline:
                raise AssertionError(
                    "standby-ring occupant never became ready")
            time.sleep(0.005)
        # fb is prefilled and published into every while-megastep now;
        # the next dispatch carries both lanes and dies mid-loop
        plan.arm("engine.step", kind="error", times=1)
        for fut, who in ((fa, "active lane"), (fb, "ring occupant")):
            try:
                fut.result(timeout=60)
            except InjectedFault:
                continue
            raise AssertionError(
                "%s survived the mid-megastep fault" % who)
        engine._whilestep_jit = real
        inv = engine.verify_pool_invariants()  # pages home, cross-checked
        if inv["used_pages"] != 0 or inv["pinned_pages"] != 0:
            raise AssertionError(
                "faulted participants leaked pages: %r" % (inv,))
        survivors = [(p, engine.submit(p, n_new))
                     for p in prompts[2:]]
        for i, (p, f) in enumerate(survivors, start=2):
            out = f.result(timeout=120)
            if not numpy.array_equal(numpy.concatenate([p, out]),
                                     expect[i]):
                raise AssertionError(
                    "survivor after the while-megastep fault diverged "
                    "from greedy generate")
    finally:
        engine._whilestep_jit = real
        plan.release()
        engine.stop()
    recs = tracer.requests()
    verify_integrity(recs)                  # raises on a broken tree
    errs = [r for r in recs
            if r["error"] and "InjectedFault" in r["error"]]
    if len(errs) != 2:
        raise AssertionError(
            "one engine.step fault must fail exactly the 2 "
            "participants, got %d errored traces" % len(errs))
    for r in errs:
        if not any(s["name"] == "decode.megastep"
                   and "error" in s["attrs"] for s in r["spans"]):
            raise AssertionError(
                "a faulted participant's trace is missing the errored "
                "decode.megastep span")
    snap = engine.metrics.snapshot()
    if engine._pool.free_pages != engine._pool.num_pages:
        raise AssertionError("pool did not refill whole after drain")
    return {
        "scenario": "whilestep_fault",
        "requests": len(prompts),
        "faulted_participants": len(errs),
        "survivor_parity_vs_generate": True,
        "pool_pages": pool_pages,
        "pages_leaked": 0,
        "standby_ring_peak": int(
            snap["gauges"].get("standby_ring_peak", 0)),
        "megastep_refills": int(
            snap["counters"].get("megastep_refills", 0)),
        "span_trees_sound": True,
        "wall_s": round(time.monotonic() - t0, 3),
    }


# ------------------------------------------------------------------- bench
def summary_record(results):
    """(record, exit_code) in the bench.py shape — metric priority in
    ONE place: scenarios completed / total once any ran."""
    done = [k for k in ("kill_one_replica_under_load",
                        "slow_replica_tail", "pool_exhaustion_storm",
                        "weight_swap_under_load",
                        "traced_flight_recorder",
                        "slo_burn_alert",
                        "whilestep_fault",
                        "fault_free_overhead") if k in results]
    if done:
        return {
            "metric": "chaos_scenarios_passed",
            "value": len(done),
            "unit": "scenarios",
            "vs_baseline": 8,
            "configs": results,
        }, 0
    return {"metric": "chaos_no_scenarios_completed", "value": None,
            "unit": None, "vs_baseline": None, "configs": results}, 1


def run_lint_leg(results):
    """The dispatch-hygiene assertion leg (ISSUE 17): every
    ``tools/veles_lint.py`` pass over the shipped tree before the
    chaos scenarios — resilience numbers for an engine whose hot path
    regressed into an implicit host sync describe a different engine
    than the one the repo ships.  Streams the bench-schema
    ``lint_clean`` record and ASSERTS zero findings."""
    import veles_lint
    findings, _, stats = veles_lint.run_check()
    record = veles_lint.clean_record(findings, stats)[0]
    print(json.dumps(record), flush=True)
    assert not findings, (
        "lint_clean leg: %d finding(s) on the shipped tree — %s"
        % (len(findings), "; ".join(str(f) for f in findings[:5])))
    results["lint_clean"] = record["configs"]


def run_bench(smoke=False, n_new=16, requests=12, seed=0):
    if smoke:
        n_new, requests = 8, 6
    vocab, max_len = 16, 64
    params = build_params(vocab=vocab, d_model=32, n_heads=2,
                          n_layers=2, max_len=max_len, seed=7)
    n_heads = 2
    prompts = mixed_length_prompts(requests, vocab, 4,
                                   max_len - n_new - 8, seed=seed + 13)
    expect = expected_rows(params, prompts, n_new, n_heads, max_len)
    results = {"model": {"vocab": vocab, "max_len": max_len},
               "requests": requests, "n_new": n_new}

    def stream():
        record, _ = summary_record(results)
        print(json.dumps(record), flush=True)

    # lint_clean first (ISSUE 17): cheap, and a dirty tree should
    # refuse the run before any scenario burns wall clock
    run_lint_leg(results)
    results["kill_one_replica_under_load"] = scenario_kill_replica(
        params, n_heads, max_len, prompts, n_new, expect)
    stream()
    results["slow_replica_tail"] = scenario_slow_replica(
        params, n_heads, max_len, prompts[:max(4, requests // 2)],
        n_new, expect)
    stream()
    results["pool_exhaustion_storm"] = scenario_pool_storm(
        params, n_heads, max_len, prompts, n_new, expect)
    stream()
    params_new = build_params(vocab=vocab, d_model=32, n_heads=2,
                              n_layers=2, max_len=max_len, seed=11)
    expect_new = expected_rows(params_new, prompts, n_new, n_heads,
                               max_len)
    results["weight_swap_under_load"] = scenario_weight_swap(
        params, params_new, n_heads, max_len, prompts, n_new, expect,
        expect_new)
    stream()
    results["traced_flight_recorder"] = scenario_traced_flight_recorder(
        params, n_heads, max_len, prompts, n_new, expect)
    stream()
    results["slo_burn_alert"] = scenario_slo_burn_alert(
        params, n_heads, max_len, prompts[:max(4, requests // 2)],
        n_new, expect)
    stream()
    results["whilestep_fault"] = scenario_whilestep_fault(
        params, n_heads, max_len, prompts[:max(6, requests // 2)],
        n_new, expect)
    stream()
    results["fault_free_overhead"] = scenario_overhead(
        params, n_heads, max_len, prompts[:4], n_new)
    stream()
    return results


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--smoke", action="store_true",
                        help="tiny sizes (CI validation)")
    parser.add_argument("--n-new", type=int, default=16)
    parser.add_argument("--requests", type=int, default=12)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="also write the final record here")
    args = parser.parse_args(argv)
    results = run_bench(smoke=args.smoke, n_new=args.n_new,
                        requests=args.requests, seed=args.seed)
    record, rc = summary_record(results)
    line = json.dumps(record)
    print(line)                  # final full record — last line wins
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            f.write(line + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
