"""Publishing — post-training report generation.

Ref: veles/publishing/::Publisher (+ HTML/PDF/Confluence backends) [M]
(SURVEY §2.1).  Gathers the run's facts (workflow, config, epochs, metrics,
plots) and renders them through a backend; in-tree backends are Markdown and
self-contained HTML (no jinja2 dependency — stdlib string formatting).
"""

from __future__ import annotations

import base64
import html
import json
import os
import time


def gather(workflow, launcher=None, plots=()):
    """Collect the report facts from a finished workflow."""
    decision = getattr(workflow, "decision", None)
    facts = {
        "workflow": workflow.name,
        "workflow_class": type(workflow).__name__,
        "generated_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "run_seconds": getattr(launcher, "run_seconds", None),
        "best_metric": getattr(decision, "best_metric", None),
        "best_epoch": getattr(decision, "best_epoch", None),
        "epochs": [],
        "units": [u.name for u in workflow],
        "plots": list(plots),
    }
    if decision is not None:
        for i, epoch in enumerate(decision.epoch_metrics):
            row = {"epoch": i + 1}
            for set_name, metrics in epoch.items():
                for key, value in metrics.items():
                    if isinstance(value, (int, float)):
                        row["%s_%s" % (set_name, key)] = value
            facts["epochs"].append(row)
    return facts


class MarkdownBackend:
    suffix = ".md"

    def render(self, facts):
        lines = ["# Training report: %s" % facts["workflow"],
                 "",
                 "- class: `%s`" % facts["workflow_class"],
                 "- generated: %s" % facts["generated_at"],
                 "- best metric: **%s** (epoch %s)"
                 % (facts["best_metric"], facts["best_epoch"])]
        if facts["run_seconds"]:
            lines.append("- run time: %.1fs" % facts["run_seconds"])
        if facts["epochs"]:
            keys = sorted({k for row in facts["epochs"] for k in row})
            lines += ["", "| " + " | ".join(keys) + " |",
                      "|" + "---|" * len(keys)]
            for row in facts["epochs"]:
                lines.append("| " + " | ".join(
                    ("%.6g" % row[k]) if isinstance(row.get(k), float)
                    else str(row.get(k, "")) for k in keys) + " |")
        lines += ["", "Units: " + ", ".join(facts["units"])]
        return "\n".join(lines) + "\n"


def _xml_cell(row, key):
    """One escaped table cell: floats formatted, everything else
    html-escaped — an unescaped & or < malforms an HTML report and
    400s a Confluence storage-format POST (shared by both backends)."""
    value = row.get(key)
    if isinstance(value, float):
        return "%.6g" % value
    return html.escape(str(value if value is not None else ""))


class HTMLBackend:
    suffix = ".html"

    def render(self, facts):
        rows = ""
        if facts["epochs"]:
            keys = sorted({k for row in facts["epochs"] for k in row})
            head = "".join("<th>%s</th>" % html.escape(k) for k in keys)
            body = ""
            for row in facts["epochs"]:
                body += "<tr>" + "".join(
                    "<td>%s</td>" % _xml_cell(row, k) for k in keys) + \
                    "</tr>"
            rows = "<table><tr>%s</tr>%s</table>" % (head, body)
        imgs = ""
        for path in facts["plots"]:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    b64 = base64.b64encode(f.read()).decode("ascii")
                imgs += ('<img src="data:image/png;base64,%s" '
                         'style="max-width:45%%; margin:4px"/>' % b64)
        return ("<!doctype html><html><head><meta charset='utf-8'>"
                "<title>%(name)s report</title></head><body>"
                "<h1>Training report: %(name)s</h1>"
                "<p>class <code>%(cls)s</code> — generated %(at)s</p>"
                "<p>best metric <b>%(best)s</b> at epoch %(epoch)s</p>"
                "%(rows)s%(imgs)s</body></html>") % {
            "name": html.escape(str(facts["workflow"])),
            "cls": html.escape(str(facts["workflow_class"])),
            "at": html.escape(str(facts["generated_at"])),
            "best": html.escape(str(facts["best_metric"])),
            "epoch": html.escape(str(facts["best_epoch"])),
            "rows": rows,
            "imgs": imgs,
        }


class JSONBackend:
    suffix = ".json"

    def render(self, facts):
        return json.dumps(facts, indent=2, default=str)


class ConfluenceBackend:
    """Confluence storage-format page body (ref: veles/publishing/
    confluence_backend [M]).  Renders the XHTML-based storage format a
    Confluence ``/rest/api/content`` POST accepts; pair with
    :func:`publish_confluence` to upload."""

    suffix = ".confluence.xml"

    def render(self, facts):
        rows = ""
        if facts["epochs"]:
            keys = sorted({k for row in facts["epochs"] for k in row})
            head = "".join("<th>%s</th>" % html.escape(k) for k in keys)
            body = ""
            for row in facts["epochs"]:
                body += "<tr>" + "".join(
                    "<td>%s</td>" % _xml_cell(row, k)
                    for k in keys) + "</tr>"
            rows = "<table><tbody><tr>%s</tr>%s</tbody></table>" % (
                head, body)
        return ("<h1>Training report: %(name)s</h1>"
                "<p>class <code>%(cls)s</code> — generated %(at)s</p>"
                "<p>best metric <strong>%(best)s</strong> at epoch "
                "%(epoch)s</p>"
                '<ac:structured-macro ac:name="code">'
                "<ac:plain-text-body><![CDATA[units: %(units)s]]>"
                "</ac:plain-text-body></ac:structured-macro>"
                "%(rows)s") % {
            "name": html.escape(str(facts["workflow"])),
            "cls": html.escape(str(facts["workflow_class"])),
            "at": html.escape(str(facts["generated_at"])),
            "best": html.escape(str(facts["best_metric"])),
            "epoch": html.escape(str(facts["best_epoch"])),
            "units": ", ".join(facts["units"]),
            "rows": rows,
        }


def publish_confluence(base_url, space_key, title, facts, auth=None):
    """Create a Confluence page holding the report (the reference's
    confluence upload flow, via the stable REST API instead of its
    XML-RPC).  ``auth`` is a (user, token) pair for basic auth; returns
    the decoded JSON response."""
    import base64 as b64
    import urllib.request
    payload = {
        "type": "page",
        "title": title,
        "space": {"key": space_key},
        "body": {"storage": {
            "value": ConfluenceBackend().render(facts),
            "representation": "storage"}},
    }
    req = urllib.request.Request(
        base_url.rstrip("/") + "/rest/api/content",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    if auth is not None:
        req.add_header(
            "Authorization", "Basic " + b64.b64encode(
                ("%s:%s" % auth).encode()).decode("ascii"))
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


class PDFBackend:
    """Print report via matplotlib PdfPages (ref: veles/publishing/
    pdf_backend [M]): a summary page plus per-metric learning curves.

    Chart choices follow the in-house dataviz method: change-over-time →
    line marks; metrics of different scales never share an axis — each
    metric gets its own small-multiple panel (single series, titled, so
    no legend is needed); recessive grid, thin 2px lines."""

    suffix = ".pdf"
    binary = True

    SURFACE = "#fcfcfb"
    INK = "#0b0b0b"
    INK2 = "#52514e"
    SERIES = "#2a78d6"

    def render(self, facts):
        import io
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages

        buf = io.BytesIO()
        with PdfPages(buf) as pdf:
            fig = plt.figure(figsize=(8.27, 11.69))   # A4 portrait
            fig.patch.set_facecolor(self.SURFACE)
            lines = [
                ("Training report: %s" % facts["workflow"], 16, self.INK),
                ("", 10, self.INK2),
                ("class %s" % facts["workflow_class"], 10, self.INK2),
                ("generated %s" % facts["generated_at"], 10, self.INK2),
                ("best metric %s (epoch %s)"
                 % (facts["best_metric"], facts["best_epoch"]), 11,
                 self.INK),
            ]
            if facts["run_seconds"]:
                lines.append(("run time %.1f s" % facts["run_seconds"],
                              10, self.INK2))
            lines.append(("units: %s" % ", ".join(facts["units"]), 9,
                          self.INK2))
            y = 0.93
            for text, size, color in lines:
                fig.text(0.08, y, text, fontsize=size, color=color,
                         family="monospace", wrap=True)
                y -= 0.035
            pdf.savefig(fig)
            plt.close(fig)

            rows = facts["epochs"]
            keys = sorted({k for row in rows for k in row
                           if k != "epoch"}) if rows else []
            if keys:
                epochs = [row["epoch"] for row in rows]
                ncols = 2
                nrows = (len(keys) + ncols - 1) // ncols
                fig, axes = plt.subplots(
                    nrows, ncols, figsize=(8.27, 2.6 * nrows),
                    squeeze=False)
                fig.patch.set_facecolor(self.SURFACE)
                for ax in axes.flat[len(keys):]:
                    ax.axis("off")
                for ax, key in zip(axes.flat, keys):
                    ys = [row.get(key) for row in rows]
                    xs = [e for e, v in zip(epochs, ys) if v is not None]
                    ax.plot(xs, [v for v in ys if v is not None],
                            color=self.SERIES, linewidth=2)
                    ax.set_title(key, fontsize=9, color=self.INK,
                                 family="monospace", loc="left")
                    ax.set_xlabel("epoch", fontsize=8, color=self.INK2)
                    from matplotlib.ticker import MaxNLocator
                    ax.xaxis.set_major_locator(
                        MaxNLocator(integer=True))
                    ax.tick_params(labelsize=7, colors=self.INK2)
                    ax.set_facecolor(self.SURFACE)
                    ax.grid(True, color="#e4e3df", linewidth=0.6)
                    for side in ("top", "right"):
                        ax.spines[side].set_visible(False)
                    for side in ("left", "bottom"):
                        ax.spines[side].set_color(self.INK2)
                fig.tight_layout()
                pdf.savefig(fig)
                plt.close(fig)
        return buf.getvalue()


BACKENDS = {"markdown": MarkdownBackend, "html": HTMLBackend,
            "json": JSONBackend, "pdf": PDFBackend,
            "confluence": ConfluenceBackend}


class Publisher:
    """Render a finished workflow's report with the chosen backends."""

    def __init__(self, backends=("markdown", "html")):
        self.backends = [BACKENDS[b]() if isinstance(b, str) else b
                         for b in backends]

    def publish(self, workflow, out_dir, launcher=None, plots=()):
        facts = gather(workflow, launcher, plots)
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for backend in self.backends:
            path = os.path.join(
                out_dir, "report_%s%s" % (facts["workflow"], backend.suffix))
            if getattr(backend, "binary", False):
                with open(path, "wb") as f:
                    f.write(backend.render(facts))
            else:
                with open(path, "w", encoding="utf-8") as f:
                    f.write(backend.render(facts))
            paths.append(path)
        return paths
