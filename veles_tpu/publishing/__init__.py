"""Publishing — post-training report generation.

Ref: veles/publishing/::Publisher (+ HTML/PDF/Confluence backends) [M]
(SURVEY §2.1).  Gathers the run's facts (workflow, config, epochs, metrics,
plots) and renders them through a backend; in-tree backends are Markdown and
self-contained HTML (no jinja2 dependency — stdlib string formatting).
"""

from __future__ import annotations

import base64
import html
import json
import os
import time


def gather(workflow, launcher=None, plots=()):
    """Collect the report facts from a finished workflow."""
    decision = getattr(workflow, "decision", None)
    facts = {
        "workflow": workflow.name,
        "workflow_class": type(workflow).__name__,
        "generated_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "run_seconds": getattr(launcher, "run_seconds", None),
        "best_metric": getattr(decision, "best_metric", None),
        "best_epoch": getattr(decision, "best_epoch", None),
        "epochs": [],
        "units": [u.name for u in workflow],
        "plots": list(plots),
    }
    if decision is not None:
        for i, epoch in enumerate(decision.epoch_metrics):
            row = {"epoch": i + 1}
            for set_name, metrics in epoch.items():
                for key, value in metrics.items():
                    if isinstance(value, (int, float)):
                        row["%s_%s" % (set_name, key)] = value
            facts["epochs"].append(row)
    return facts


class MarkdownBackend:
    suffix = ".md"

    def render(self, facts):
        lines = ["# Training report: %s" % facts["workflow"],
                 "",
                 "- class: `%s`" % facts["workflow_class"],
                 "- generated: %s" % facts["generated_at"],
                 "- best metric: **%s** (epoch %s)"
                 % (facts["best_metric"], facts["best_epoch"])]
        if facts["run_seconds"]:
            lines.append("- run time: %.1fs" % facts["run_seconds"])
        if facts["epochs"]:
            keys = sorted({k for row in facts["epochs"] for k in row})
            lines += ["", "| " + " | ".join(keys) + " |",
                      "|" + "---|" * len(keys)]
            for row in facts["epochs"]:
                lines.append("| " + " | ".join(
                    ("%.6g" % row[k]) if isinstance(row.get(k), float)
                    else str(row.get(k, "")) for k in keys) + " |")
        lines += ["", "Units: " + ", ".join(facts["units"])]
        return "\n".join(lines) + "\n"


class HTMLBackend:
    suffix = ".html"

    def render(self, facts):
        rows = ""
        if facts["epochs"]:
            keys = sorted({k for row in facts["epochs"] for k in row})
            head = "".join("<th>%s</th>" % html.escape(k) for k in keys)
            body = ""
            for row in facts["epochs"]:
                body += "<tr>" + "".join(
                    "<td>%s</td>" % (("%.6g" % row[k])
                                     if isinstance(row.get(k), float)
                                     else row.get(k, "")) for k in keys) + \
                    "</tr>"
            rows = "<table><tr>%s</tr>%s</table>" % (head, body)
        imgs = ""
        for path in facts["plots"]:
            if os.path.exists(path):
                with open(path, "rb") as f:
                    b64 = base64.b64encode(f.read()).decode("ascii")
                imgs += ('<img src="data:image/png;base64,%s" '
                         'style="max-width:45%%; margin:4px"/>' % b64)
        return ("<!doctype html><html><head><meta charset='utf-8'>"
                "<title>%(name)s report</title></head><body>"
                "<h1>Training report: %(name)s</h1>"
                "<p>class <code>%(cls)s</code> — generated %(at)s</p>"
                "<p>best metric <b>%(best)s</b> at epoch %(epoch)s</p>"
                "%(rows)s%(imgs)s</body></html>") % {
            "name": html.escape(str(facts["workflow"])),
            "cls": html.escape(str(facts["workflow_class"])),
            "at": facts["generated_at"],
            "best": facts["best_metric"],
            "epoch": facts["best_epoch"],
            "rows": rows,
            "imgs": imgs,
        }


class JSONBackend:
    suffix = ".json"

    def render(self, facts):
        return json.dumps(facts, indent=2, default=str)


BACKENDS = {"markdown": MarkdownBackend, "html": HTMLBackend,
            "json": JSONBackend}


class Publisher:
    """Render a finished workflow's report with the chosen backends."""

    def __init__(self, backends=("markdown", "html")):
        self.backends = [BACKENDS[b]() if isinstance(b, str) else b
                         for b in backends]

    def publish(self, workflow, out_dir, launcher=None, plots=()):
        facts = gather(workflow, launcher, plots)
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for backend in self.backends:
            path = os.path.join(
                out_dir, "report_%s%s" % (facts["workflow"], backend.suffix))
            with open(path, "w", encoding="utf-8") as f:
                f.write(backend.render(facts))
            paths.append(path)
        return paths
