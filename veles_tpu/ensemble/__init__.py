"""Ensemble training and combined evaluation.

Ref: veles/ensemble/ [M] (SURVEY §2.1): train N instances of a workflow
(seed variations), collect per-model results, then evaluate the combined
model.  TPU-native: members train sequentially in-process (one TPU
attachment); combination averages the members' softmax outputs over the
validation set with one jitted eval per member.
"""

from __future__ import annotations

import numpy

from veles_tpu.logger import Logger
from veles_tpu.loader.base import VALID


class EnsembleTrainer(Logger):
    """Train ``size`` members of a sample module (``run(load, main)``
    convention), seeds base_seed+i, and combine them."""

    def __init__(self, module, size=4, base_seed=1, build_kwargs=None):
        self.module = module
        self.size = size
        self.base_seed = base_seed
        self.build_kwargs = dict(build_kwargs or {})
        self.members = []       # (seed, workflow, summary)

    def train(self):
        from veles_tpu.samples import run_sample
        for i in range(self.size):
            seed = self.base_seed + i
            wf = run_sample(self.module, seed=seed,
                            build_kwargs=self.build_kwargs)
            summary = {"seed": seed,
                       "best_metric": wf.decision.best_metric,
                       "best_epoch": wf.decision.best_epoch}
            self.members.append((seed, wf, summary))
            self.info("member %d/%d (seed %d): best %s", i + 1, self.size,
                      seed, summary["best_metric"])
        return self

    # -- combined evaluation -------------------------------------------------
    def _eval_fn(self):
        """ONE compiled eval forward for all members: topologies are
        identical, state is an argument — member 0's jit serves every
        member's state, so combining N members costs one XLA compile."""
        return self.members[0][1]._fused_runner.eval_forward()

    def evaluate_combined(self):
        """Average member probabilities on the validation set → n_err.

        All members must share the loader layout (same seed-independent
        dataset, e.g. real MNIST or a fixed-stream synthetic set).
        """
        if not self.members:
            raise ValueError("train() first")
        _, wf0, _ = self.members[0]
        loader = wf0.loader
        begin, end = loader.class_offsets()[VALID]
        if end <= begin:
            raise ValueError("no validation samples to combine on")
        data = loader.original_data.devmem[begin:end]
        labels = numpy.asarray(loader.original_labels.mem[begin:end])
        total = None
        per_member_err = []
        eval_fn = self._eval_fn()
        for _, wf, _ in self.members:
            probs = numpy.asarray(eval_fn(wf._fused_runner.state, data))
            per_member_err.append(
                int((probs.argmax(1) != labels).sum()))
            total = probs if total is None else total + probs
        ens_err = int((total.argmax(1) != labels).sum())
        return {"members": per_member_err, "ensemble_n_err": ens_err,
                "count": len(labels)}


def train_ensemble(module, size=4, base_seed=1, build_kwargs=None):
    """One-call convenience: train + combined evaluation."""
    trainer = EnsembleTrainer(module, size=size, base_seed=base_seed,
                              build_kwargs=build_kwargs).train()
    return trainer, trainer.evaluate_combined()
