"""Ensemble training and combined evaluation.

Ref: veles/ensemble/ [M] (SURVEY §2.1): train N instances of a workflow
(seed variations), collect per-model results, then evaluate the combined
model.  Combination averages the members' softmax outputs over the
validation set with one jitted eval per member.

``train(workers=N)`` trains members across N CPU worker subprocesses
(the reference evaluated members across slaves, SURVEY §3.5): each worker
trains one member and ships its full snapshot back; the parent restores
them, so parallel members are indistinguishable from sequential ones
trained on the same platform.  ``workers=0`` (default) trains members
sequentially in-process (on the parent's accelerator).
"""

from __future__ import annotations

import numpy

from veles_tpu.logger import Logger
from veles_tpu.loader.base import VALID


class EnsembleTrainer(Logger):
    """Train ``size`` members of a sample module (``run(load, main)``
    convention), seeds base_seed+i, and combine them."""

    def __init__(self, module, size=4, base_seed=1, build_kwargs=None):
        self.module = module
        self.size = size
        self.base_seed = base_seed
        self.build_kwargs = dict(build_kwargs or {})
        self.members = []       # (seed, workflow, summary)

    def train(self, workers=0):
        if workers > 0:
            return self._train_parallel(workers)
        from veles_tpu.samples import run_sample
        for i in range(self.size):
            seed = self.base_seed + i
            wf = run_sample(self.module, seed=seed,
                            build_kwargs=self.build_kwargs)
            summary = {"seed": seed,
                       "best_metric": wf.decision.best_metric,
                       "best_epoch": wf.decision.best_epoch}
            self.members.append((seed, wf, summary))
            self.info("member %d/%d (seed %d): best %s", i + 1, self.size,
                      seed, summary["best_metric"])
        return self

    def _build_member(self, seed):
        """Build + initialize (but do not train) one member workflow —
        the restore target for a worker-trained snapshot."""
        from veles_tpu import prng
        prng.reset()
        prng.seed_all(seed)
        holder = {}

        def load(workflow_cls, **kwargs):
            kwargs.update(self.build_kwargs)
            wf = workflow_cls(None, **kwargs)
            holder["wf"] = wf
            return wf

        def main():
            holder["wf"].initialize()

        self.module.run(load, main)
        return holder["wf"]

    def _train_parallel(self, workers):
        import os
        import pickle
        import tempfile

        from veles_tpu import snapshotter
        from veles_tpu.config import root
        from veles_tpu.subproc import plain_config, run_workers

        config_snapshot = plain_config(root.as_dict())
        with tempfile.TemporaryDirectory(prefix="ensemble_") as tmp:
            seeds = [self.base_seed + i for i in range(self.size)]
            specs = [{
                "config": config_snapshot,
                "module": self.module.__name__,
                "seed": seed,
                "build_kwargs": self.build_kwargs,
                "snapshot_path": os.path.join(tmp, "member_%d.pickle"
                                              % seed),
            } for seed in seeds]
            summaries = run_workers("veles_tpu.ensemble.train_worker",
                                    specs, workers)
            for seed, spec, summary in zip(seeds, specs, summaries):
                with open(spec["snapshot_path"], "rb") as f:
                    payload = pickle.load(f)
                wf = self._build_member(seed)
                snapshotter.restore(wf, payload)
                self.members.append((seed, wf, {
                    "seed": seed,
                    "best_metric": summary["best_metric"],
                    "best_epoch": summary["best_epoch"]}))
                self.info("member (seed %d): best %s [worker]", seed,
                          summary["best_metric"])
        return self

    # -- combined evaluation -------------------------------------------------
    def _eval_fn(self):
        """ONE compiled eval forward for all members: topologies are
        identical, state is an argument — member 0's jit serves every
        member's state, so combining N members costs one XLA compile."""
        return self.members[0][1]._fused_runner.eval_forward()

    def evaluate_combined(self):
        """Average member probabilities on the validation set → n_err.

        All members must share the loader layout (same seed-independent
        dataset, e.g. real MNIST or a fixed-stream synthetic set).
        """
        if not self.members:
            raise ValueError("train() first")
        _, wf0, _ = self.members[0]
        loader = wf0.loader
        begin, end = loader.class_offsets()[VALID]
        if end <= begin:
            raise ValueError("no validation samples to combine on")
        data = loader.original_data.devmem[begin:end]
        labels = numpy.asarray(loader.original_labels.mem[begin:end])
        total = None
        per_member_err = []
        eval_fn = self._eval_fn()
        for _, wf, _ in self.members:
            probs = numpy.asarray(eval_fn(wf._fused_runner.state, data))
            per_member_err.append(
                int((probs.argmax(1) != labels).sum()))
            total = probs if total is None else total + probs
        ens_err = int((total.argmax(1) != labels).sum())
        return {"members": per_member_err, "ensemble_n_err": ens_err,
                "count": len(labels)}


def train_ensemble(module, size=4, base_seed=1, build_kwargs=None,
                   workers=0):
    """One-call convenience: train + combined evaluation."""
    trainer = EnsembleTrainer(module, size=size, base_seed=base_seed,
                              build_kwargs=build_kwargs).train(
                                  workers=workers)
    return trainer, trainer.evaluate_combined()
