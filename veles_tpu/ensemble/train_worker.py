"""Subprocess member trainer for parallel ensembles.

Ref: veles/ensemble evaluated member runs across slaves (SURVEY §2.1/§3.5);
this worker is one member: reads a JSON spec on stdin (config tree, sample
module, seed, snapshot path), trains on the HOST platform, pickles the
full workflow snapshot state to ``snapshot_path`` and prints the member
summary as one JSON line.  The parent restores the snapshot into its own
workflow instance, so parallel members are indistinguishable from
sequentially-trained ones.
"""

from __future__ import annotations

import importlib
import json
import pickle
import sys


def main():
    spec = json.load(sys.stdin)
    import jax
    jax.config.update("jax_platforms", "cpu")  # never claim the TPU tunnel

    from veles_tpu.config import root
    root.update(spec["config"])
    module = importlib.import_module(spec["module"])
    from veles_tpu.samples import run_sample
    wf = run_sample(module, seed=spec["seed"],
                    build_kwargs=spec.get("build_kwargs"))
    import veles_tpu
    from veles_tpu import snapshotter
    payload = {
        "format": snapshotter.FORMAT,
        "framework_version": veles_tpu.__version__,
        "workflow_name": wf.name,
        "epoch": int(wf.loader.epoch_number),
        "best_metric": wf.decision.best_metric,
        "state": wf.snapshot_state(),
        "config": root.as_dict(),
    }
    with open(spec["snapshot_path"], "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    print(json.dumps({"seed": spec["seed"],
                      "best_metric": wf.decision.best_metric,
                      "best_epoch": wf.decision.best_epoch}))


if __name__ == "__main__":
    main()
