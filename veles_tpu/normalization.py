"""Feature normalization strategies for loaders.

Ref: veles/normalization.py::NoneNormalizer/LinearNormalizer/
MeanDispersionNormalizer + pointwise/exp variants [H] (SURVEY §2.1).
Contract preserved: a normalizer ``analyze()``s the training data to fit its
statistics, then ``apply()``s the same transform to every set; it is
picklable so snapshots (and served models) reproduce the exact input
transform.  Statistics are computed with numpy at load time (host-side, once
per dataset) — the per-minibatch path stays on device untouched.
"""

from __future__ import annotations

import numpy

#: registry: name -> normalizer class (ref: the reference's class registry
#: keyed by the loader's ``normalization_type`` config string)
NORMALIZERS = {}


def register(name):
    def deco(cls):
        NORMALIZERS[name] = cls
        return cls
    return deco


def from_spec(name, **kwargs):
    """Instantiate a normalizer by config name."""
    cls = NORMALIZERS.get(name)
    if cls is None:
        raise ValueError("unknown normalization_type %r (known: %s)" %
                         (name, ", ".join(sorted(NORMALIZERS))))
    return cls(**kwargs)


class NormalizerBase:
    """analyze() fits statistics; apply()/denormalize() use them."""

    #: attributes persisted through pickling (all plain numpy/python)
    state_attrs = ()

    def analyze(self, data):
        """Fit statistics from (train) data of shape (N, ...features)."""

    def apply(self, data):
        """Return the normalized copy of ``data`` (never in-place)."""
        raise NotImplementedError

    def denormalize(self, data):
        raise NotImplementedError

    @property
    def is_fitted(self):
        """True once analyze() has produced every statistic (stateless
        normalizers are always fitted)."""
        return all(getattr(self, attr) is not None
                   for attr in self.state_attrs)


@register("none")
class NoneNormalizer(NormalizerBase):
    """Identity (ref: NoneNormalizer [H])."""

    def apply(self, data):
        return numpy.asarray(data)

    def denormalize(self, data):
        return numpy.asarray(data)


@register("linear")
class LinearNormalizer(NormalizerBase):
    """Per-feature min/max mapping onto [interval_min, interval_max]
    (default [-1, 1]) — ref: LinearNormalizer [H]."""

    state_attrs = ("vmin", "vmax", "interval")

    def __init__(self, interval=(-1.0, 1.0)):
        self.interval = tuple(interval)
        self.vmin = None
        self.vmax = None

    def analyze(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        self.vmin = flat.min(axis=0)
        self.vmax = flat.max(axis=0)

    def _scales(self):
        lo, hi = self.interval
        span = numpy.where(self.vmax > self.vmin, self.vmax - self.vmin, 1.0)
        return lo, (hi - lo) / span

    def apply(self, data):
        data = numpy.asarray(data, numpy.float32)
        lo, scale = self._scales()
        flat = data.reshape(len(data), -1)
        out = lo + (flat - self.vmin) * scale
        return out.reshape(data.shape).astype(numpy.float32)

    def denormalize(self, data):
        data = numpy.asarray(data, numpy.float32)
        lo, scale = self._scales()
        flat = data.reshape(len(data), -1)
        out = self.vmin + (flat - lo) / scale
        return out.reshape(data.shape).astype(numpy.float32)


@register("mean_disp")
class MeanDispersionNormalizer(NormalizerBase):
    """(x - mean) / (max - min), per feature — ref:
    MeanDispersionNormalizer [H] (mean subtraction with dispersion scaling,
    the AlexNet-era input pipeline default)."""

    state_attrs = ("mean", "disp")

    def __init__(self):
        self.mean = None
        self.disp = None

    def analyze(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        self.mean = flat.mean(axis=0)
        span = flat.max(axis=0) - flat.min(axis=0)
        self.disp = numpy.where(span > 0, span, 1.0)

    def apply(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        return ((flat - self.mean) / self.disp).reshape(
            data.shape).astype(numpy.float32)

    def denormalize(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        return (flat * self.disp + self.mean).reshape(
            data.shape).astype(numpy.float32)


@register("pointwise")
class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map fitted so each feature lands in [-1, 1],
    stored as explicit (add, mul) arrays — ref: pointwise normalizer [M]."""

    state_attrs = ("add", "mul")

    def __init__(self):
        self.add = None
        self.mul = None

    def analyze(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        vmin, vmax = flat.min(axis=0), flat.max(axis=0)
        span = numpy.where(vmax > vmin, vmax - vmin, 1.0)
        self.mul = 2.0 / span
        self.add = -1.0 - vmin * self.mul

    def apply(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        return (flat * self.mul + self.add).reshape(
            data.shape).astype(numpy.float32)

    def denormalize(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        return ((flat - self.add) / self.mul).reshape(
            data.shape).astype(numpy.float32)


@register("exp")
class ExponentNormalizer(NormalizerBase):
    """Stable softmax-style squash per sample: exp(x - max) / sum —
    ref: ExponentNormalizer [M].  Stateless; not invertible (denormalize
    raises)."""

    def analyze(self, data):
        pass

    def apply(self, data):
        data = numpy.asarray(data, numpy.float32)
        flat = data.reshape(len(data), -1)
        shifted = numpy.exp(flat - flat.max(axis=1, keepdims=True))
        out = shifted / shifted.sum(axis=1, keepdims=True)
        return out.reshape(data.shape).astype(numpy.float32)

    def denormalize(self, data):
        raise NotImplementedError("exp normalization is not invertible")


@register("external_mean")
class ExternalMeanNormalizer(NormalizerBase):
    """Subtract a caller-provided mean sample (ref: external mean / mean
    image subtraction used by the ImageNet pipeline [M])."""

    state_attrs = ("mean",)

    def __init__(self, mean=None):
        self.mean = None if mean is None else numpy.asarray(
            mean, numpy.float32)

    def analyze(self, data):
        if self.mean is None:  # fall back to the dataset mean image
            self.mean = numpy.asarray(data, numpy.float32).mean(axis=0)

    def apply(self, data):
        data = numpy.asarray(data, numpy.float32)
        return (data - self.mean).astype(numpy.float32)

    def denormalize(self, data):
        return (numpy.asarray(data, numpy.float32) +
                self.mean).astype(numpy.float32)
