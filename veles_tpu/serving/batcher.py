"""Dynamic micro-batching — coalesce concurrent requests into one dispatch.

The request-traffic half of the serving subsystem (ISSUE 1): the direct
REST path pays one device dispatch per HTTP request, so concurrent
clients on the ThreadingHTTPServer serialize on the device.
:class:`MicroBatcher` puts a bounded queue and a worker thread between
the handler threads and the jitted forward:

- handler threads :meth:`submit` their rows and block on a future;
- the worker drains whatever is queued (waiting ``batch_wait_s`` for
  stragglers while the batch is short), concatenates the rows, pads to
  the next power-of-two BUCKET, runs ONE forward, and scatters the
  result rows back to the futures.

Buckets keep the jit cache bounded (log2(max_batch) programs, not one
per distinct batch size — the TVM/TensorFlow-Serving static-shape
trick) and are warmed at :meth:`start` so every program is compiled
before traffic arrives.  Admission control is explicit: a full queue
raises :class:`Overloaded` (HTTP 429 + ``Retry-After`` upstream) and a
request queued past its deadline is SHED with
:class:`DeadlineExceeded` instead of wasting a dispatch on a client
that has long since timed out.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck, tracing, xfer
from veles_tpu.serving.metrics import ServingMetrics


class Overloaded(RuntimeError):
    """Admission refused: the queue is full (serve as HTTP 429)."""

    def __init__(self, retry_after=0.1):
        super().__init__("serving queue full, retry after %.3fs"
                         % retry_after)
        self.retry_after = retry_after


class PoolExhausted(Overloaded):
    """Admission refused on RESOURCE pressure, not queue length: the
    paged KV pool (serving/kv_pool.py) cannot cover the page demand
    already queued ahead of this request, so admitting it would only
    let it sit until its deadline.  An :class:`Overloaded` subclass —
    upstream handlers serve the same HTTP 429 + ``Retry-After`` — but
    distinguishable, so clients and tests can tell "queue full" from
    "KV memory full".  A request admitted BEFORE the pool tightened
    still queues (the engine retries its page reservation every tick)
    and sheds 503 at its deadline: pressure never wedges a lane."""

    def __init__(self, needed, budget, retry_after=0.25):
        RuntimeError.__init__(
            self, "kv page pool exhausted: request needs %d pages but "
                  "the queued demand already covers the %d-page budget; "
                  "retry after %.3fs" % (needed, budget, retry_after))
        self.needed = needed
        self.budget = budget
        self.retry_after = retry_after


class DeadlineExceeded(RuntimeError):
    """Request spent longer than its deadline queued (serve as 503)."""


class _Item:
    __slots__ = ("rows", "future", "t_enq", "deadline", "trace",
                 "tspan")

    def __init__(self, rows, deadline_s):
        self.rows = rows
        self.future = Future()
        self.t_enq = time.monotonic()
        self.deadline = self.t_enq + deadline_s
        #: tracing (ISSUE 12): request context + open queue-wait span
        self.trace = None
        self.tspan = None


def batch_buckets(max_batch):
    """The power-of-two bucket ladder up to (and including) max_batch."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


class MicroBatcher(Logger):
    """Coalesce concurrent ``forward`` calls into padded batched dispatches.

    ``forward``: batch ndarray (b, *sample_shape) -> ndarray (b, ...);
    rows beyond the real count are zero padding and their outputs are
    discarded.  ``sample_shape`` (when known) lets :meth:`start` warm
    every bucket's compile before traffic arrives; without it the first
    request of each bucket pays the compile.
    """

    #: lock-discipline map (ISSUE 15): handler threads vs the worker.
    #: ``sample_shape`` and ``_dispatch_ewma`` are written by the
    #: worker after a dispatch and read on the admission path, so they
    #: ride the lock too.
    _guarded_by = {
        "_queue": "_cond",
        "_stop": "_cond",
        "_dispatch_ewma": "_cond",
        "sample_shape": "_cond",
    }

    def __init__(self, forward, max_batch=64, queue_depth=128,
                 batch_wait_s=0.002, deadline_s=2.0, sample_shape=None,
                 dtype=numpy.float32, metrics=None, name="predict",
                 faults=None, tracer=None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.name = name
        #: optional serving/faults.py FaultPlan (ISSUE 10) — the
        #: batcher.* sites are one is-None check when unarmed
        self._faults = faults
        #: optional serving/tracing.py SpanTracer (ISSUE 12), same
        #: unarmed-is-one-check discipline
        self._tracer = tracer
        self.forward = forward
        self.max_batch = int(max_batch)
        self.buckets = batch_buckets(self.max_batch)
        self.queue_depth = int(queue_depth)
        self.batch_wait_s = float(batch_wait_s)
        self.deadline_s = float(deadline_s)
        self.sample_shape = (tuple(sample_shape)
                             if sample_shape is not None else None)
        self.dtype = dtype
        self.metrics = metrics or ServingMetrics(name)
        self._queue = collections.deque()
        self._cond = lockcheck.make_condition("batcher._cond")
        self._thread = None
        self._stop = False
        #: EWMA of dispatch seconds — the Retry-After estimate
        self._dispatch_ewma = 0.05

    # --------------------------------------------------------------- lifecycle
    def start(self):
        # lint: allow(lock-discipline): pre-start warmup — no worker thread exists yet
        shape = self.sample_shape
        if shape is not None:
            for b in self.buckets:
                self.forward(numpy.zeros((b,) + shape, self.dtype))
            self.debug("warmed %d batch buckets %s", len(self.buckets),
                       self.buckets)
        with self._cond:
            self._stop = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="micro-batcher-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # ------------------------------------------------------------------ client
    def submit(self, rows):
        """Block until ``rows`` (n, *sample) are served; returns the n
        output rows.  Raises :class:`Overloaded` when the queue is full
        and :class:`DeadlineExceeded` when the request was shed."""
        rows = numpy.asarray(rows, self.dtype)
        if rows.ndim < 1 or len(rows) < 1:
            raise ValueError("submit needs at least one row")
        if self._faults is not None:
            self._faults.fire("batcher.submit")
        tctx, own_root = None, False
        if self._tracer is not None:
            tctx, own_root = tracing.join_or_root(
                self._tracer, "batch.request", "batch",
                attrs={"engine": self.name})
            if tctx is tracing.SAMPLED_OUT:
                tctx = None
        try:
            item = self._admit(rows, tctx, own_root)
        except Exception as e:
            if own_root:
                tctx.tracer.finish_request(tctx, error=e)
            raise
        return item.future.result()

    def _admit(self, rows, tctx, own_root):
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("micro-batcher is not running")
            # shape-check HERE, per request: one malformed request must
            # fail alone (400), never poison the batch it would have
            # been coalesced into.  The canonical shape comes from
            # warmup or is adopted after the first SUCCESSFUL dispatch
            # (a bad first request must not poison the server either);
            # until then _take_batch keeps batches shape-homogeneous.
            if self.sample_shape is not None \
                    and rows.shape[1:] != self.sample_shape:
                raise ValueError(
                    "input rows shaped %r do not match the served "
                    "sample shape %r"
                    % (tuple(rows.shape[1:]), self.sample_shape))
            if len(self._queue) >= self.queue_depth:
                self.metrics.record_reject()
                raise Overloaded(retry_after=max(
                    0.01, self._dispatch_ewma))
            item = _Item(rows, self.deadline_s)
            if tctx is not None:
                item.trace = tctx
                item.tspan = tctx.tracer.begin(
                    tctx, "queue.wait", cat="queue",
                    attrs={"engine": self.name})
                if own_root:
                    item.future.add_done_callback(
                        lambda f, ctx=tctx:
                        tracing.finish_from_future(ctx, f))
            self._queue.append(item)
            self.metrics.record_enqueue()
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self._cond.notify()
        return item

    # ------------------------------------------------------------------ worker
    def _take_batch(self):   # hot-path
        """Pop a coalescible batch: the oldest request plus whatever else
        fits within max_batch, lingering ``batch_wait_s`` for stragglers
        while short.  Returns (items, expired) — expired are already past
        their deadline and must be shed, not dispatched."""
        items, expired, n = [], [], 0
        with self._cond:
            while not self._queue and not self._stop:
                self._cond.wait()
            if self._stop and not self._queue:
                return items, expired
            t_close = time.monotonic() + self.batch_wait_s
            while True:
                while self._queue and n < self.max_batch:
                    head = self._queue[0]
                    size = len(head.rows)
                    if items and n + size > self.max_batch:
                        break
                    if items and head.rows.shape[1:] != \
                            items[0].rows.shape[1:]:
                        # pre-adoption only (submit rejects mismatches
                        # once a canonical shape exists): never coalesce
                        # mixed shapes — the odd one out dispatches
                        # alone and fails alone
                        break
                    self._queue.popleft()
                    if time.monotonic() > head.deadline:
                        expired.append(head)
                        continue
                    items.append(head)
                    n += size
                remaining = t_close - time.monotonic()
                if n >= self.max_batch or remaining <= 0 or self._stop:
                    break
                self._cond.wait(remaining)
            # boundary sweep (the ISSUE 13 discipline, applied here
            # too): shed EVERY expired queued item at the batch
            # boundary, not only those this batch's pops happen to
            # reach — a deep-queue item must not sit past its deadline
            # just because the head keeps the worker busy
            if self._queue:
                now = time.monotonic()
                if any(now > it.deadline for it in self._queue):
                    keep = collections.deque()
                    for it in self._queue:
                        (expired if now > it.deadline
                         else keep).append(it)
                    self._queue = keep
            self.metrics.set_gauge("queue_depth", len(self._queue))
        return items, expired

    def _dispatch(self, items):   # hot-path
        """Concatenate, pad to a bucket, forward ONCE, scatter rows back.
        A single oversized request (rows > max_batch) is chunked over
        several max_batch dispatches."""
        now = time.monotonic()
        for it in items:
            # close queue-wait spans BEFORE the fault site: an injected
            # dispatch error fails these clients, and their finished
            # trees must carry no unclosed spans
            if it.tspan is not None:
                it.trace.tracer.end(it.tspan, attrs={
                    "wait_s": round(now - it.t_enq, 6)})
                it.tspan = None
        if self._faults is not None:
            # inside the worker's dispatch try: an injected error rides
            # the real fault-isolation path (fails the batch's clients,
            # never the worker)
            self._faults.fire("batcher.dispatch")
        if lockcheck._witness is not None:
            lockcheck._witness.dispatch("batcher.dispatch")
        x = numpy.concatenate([it.rows for it in items]) \
            if len(items) > 1 else items[0].rows
        outs = []
        for lo in range(0, len(x), self.max_batch):
            chunk = x[lo:lo + self.max_batch]
            real = len(chunk)
            bucket = next(b for b in self.buckets if b >= real)
            if bucket > real:
                pad = numpy.zeros((bucket - real,) + chunk.shape[1:],
                                  chunk.dtype)
                chunk = numpy.concatenate([chunk, pad])
            t0 = time.monotonic()
            # explicit boundary both ways (ISSUE 17): stage the padded
            # chunk via device_put, read the result back via
            # device_get — the old `numpy.asarray(self.forward(...))`
            # was an implicit device→host sync (host-sync lint find).
            # forward itself is USER code (a jitted model, or a plain
            # host function) — its internal transfer policy is the
            # user's, so it runs inside the declared xfer.boundary()
            # while the batcher's own loop stays under the witness
            with xfer.boundary():
                out = xfer.to_host(self.forward(xfer.to_device(chunk)))
            with self._cond:
                # the admission path reads this EWMA for Retry-After:
                # the update must not race it (ISSUE 15 lint find)
                self._dispatch_ewma = (0.8 * self._dispatch_ewma
                                       + 0.2 * (time.monotonic() - t0))
            if self._tracer is not None:
                # xfer.to_host above already fenced the result — no
                # extra block_until_ready needed on this path
                self._tracer.add_many(
                    [it.trace for it in items], "batch.dispatch",
                    "batch", t0, time.monotonic(),
                    attrs={"rows": real, "bucket": bucket,
                           "backend": "xla"})
            outs.append(out[:real])
            # histogram the REAL coalesced rows, not the bucket padding —
            # the coalescing evidence must not be inflated by zero rows
            self.metrics.record_dispatch(
                real, queue_waits=[now - it.t_enq for it in items]
                if lo == 0 else ())
        out = numpy.concatenate(outs) if len(outs) > 1 else outs[0]
        with self._cond:
            if self.sample_shape is None:
                # adopt the canonical shape only once the forward
                # PROVED it — under the lock: _admit's shape check
                # reads it concurrently (ISSUE 15 lint find)
                self.sample_shape = x.shape[1:]
        offset = 0
        for it in items:
            n = len(it.rows)
            it.future.set_result(out[offset:offset + n])
            offset += n

    def _worker(self):
        # the transfer-guard witness is entered ON this thread (JAX
        # guard state is thread-local); a null context when unarmed
        with xfer.guard():
            self._serve_batches()

    def _serve_batches(self):   # hot-path
        while True:
            items, expired = self._take_batch()
            for it in expired:
                self.metrics.record_shed()
                if it.tspan is not None:
                    it.trace.tracer.end(it.tspan, error="shed")
                    it.tspan = None
                it.future.set_exception(DeadlineExceeded(
                    "request shed after %.3fs in queue (deadline %.3fs)"
                    % (time.monotonic() - it.t_enq, self.deadline_s)))
            if not items:
                with self._cond:
                    # read under the lock (ISSUE 15 lint find): stop()
                    # publishes the flag from another thread
                    stopping = self._stop
                if stopping:
                    return
                continue
            try:
                self._dispatch(items)
            except Exception as e:   # noqa: BLE001 — delivered to clients
                self.metrics.record_error()
                self.warning("dispatch failed: %s", e)
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(e)
