"""Declarative SLOs with multi-window error-budget burn-rate alerting
(ISSUE 14).

An :class:`Objective` states what the serving tier promises —
availability ("99.9% of engine dispatches succeed"), latency ("95% of
TTFTs under 500 ms", "99% of decode steps under 50 ms"), shed rate
("under 1% of admitted traffic shed or refused") — and the
:class:`SLOMonitor` continuously answers whether the promise holds,
the way production systems alert on it: as ERROR-BUDGET BURN RATES
over several windows of the :class:`~veles_tpu.serving.timeseries.
TimeSeriesStore`'s rings, not as instantaneous threshold crossings.

BURN RATE: with target ``T`` the error budget is ``1 - T``; a window
whose bad-event fraction is ``E`` burns the budget at ``E / (1-T)``×
the sustainable pace.  Burn 1.0 = exactly on budget; 10× on a 99.9%
objective means the month's budget gone in ~3 days.  The monitor
evaluates every objective over a SHORT and a LONG window (defaults 60
s / 300 s) and runs the standard multi-window state machine per
objective (gauge ``slo_state{objective=}``):

- OK (0) → WARN (1): the short window burns ≥ ``warn_burn`` (budget
  is being spent faster than sustainable — worth a look, not a page).
- WARN → PAGE (2): EVERY window burns ≥ ``page_burn`` — the long
  window confirms the burn is sustained (a lone spike that already
  passed cannot page), the short window confirms it is still
  happening (a long-ago incident cannot keep paging).  Counted on the
  transition as ``slo_pages_total``.
- PAGE/WARN → OK: the short window's burn drops below ``warn_burn``
  (the budget-relevant bleeding stopped).

A window with fewer than ``min_events`` events holds its previous
state — one failed request at 3 a.m. on an idle fleet is not a page.

ROUTER HOOK (the ISSUE 14 contract): objectives are evaluated PER
SOURCE — each replica's metrics row separately — and a replica whose
objective transitions to PAGE is reported to the PR 10
:class:`~veles_tpu.serving.router.HealthChecker` via
``note_slo_page(replica)``: the burn counts exactly like a failed
health probe, so ``fail_threshold`` consecutive paging scans
quarantine the replica through the existing circuit-breaker/drain
path (exactly-once preserved; the half-open probe re-admits it).  A
burn the whole fleet shares (every source paging) is NOT fed to the
checker — quarantining everyone is an outage, not a mitigation.

``sample_once()`` (alias ``step()``) is public and synchronous;
``serve_lm`` registers it as the store's post-tick listener so
objectives advance once per sampling window.  ``GET /slo.json``
serves :meth:`SLOMonitor.snapshot` (strict JSON, shared monotonic
``sampled_at`` stamp).

Objective file format (``serve_lm(slo=)`` / ``--serve-slo FILE``)::

    {"windows_s": [60, 300], "warn_burn": 1.0, "page_burn": 2.0,
     "objectives": [
       {"name": "availability", "kind": "availability",
        "target": 0.999},
       {"name": "ttft", "kind": "latency", "series": "ttft",
        "threshold_s": 0.5, "target": 0.95},
       {"name": "decode", "kind": "latency", "series": "decode_step",
        "threshold_s": 0.05, "target": 0.99},
       {"name": "shed", "kind": "shed_rate", "target": 0.99}]}

(for ``shed_rate`` the target is the fraction of admitted traffic
NOT shed/refused — the same "good fraction" convention as the rest.)
"""

from __future__ import annotations

import json
import threading

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck
from veles_tpu.serving.metrics import ServingMetrics, monotonic_offset

KINDS = ("availability", "latency", "shed_rate")


class Objective:
    """One declarative SLO; see the module docstring for semantics."""

    def __init__(self, name, kind, target, series=None,
                 threshold_s=None):
        if kind not in KINDS:
            raise ValueError("objective kind %r (one of %r)"
                             % (kind, KINDS))
        if not 0.0 < float(target) < 1.0:
            raise ValueError("target must be in (0, 1), got %r"
                             % (target,))
        if kind == "latency":
            if series not in ("ttft", "decode_step", "latency",
                              "queue_wait"):
                raise ValueError(
                    "latency objective needs series= one of ttft/"
                    "decode_step/latency/queue_wait (got %r)"
                    % (series,))
            if threshold_s is None or float(threshold_s) <= 0:
                raise ValueError("latency objective needs "
                                 "threshold_s > 0")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.series = series
        self.threshold_s = (float(threshold_s)
                            if threshold_s is not None else None)

    @property
    def budget(self):
        return 1.0 - self.target

    @classmethod
    def from_dict(cls, d):
        return cls(d["name"], d["kind"], d["target"],
                   series=d.get("series"),
                   threshold_s=d.get("threshold_s"))

    def to_dict(self):
        out = {"name": self.name, "kind": self.kind,
               "target": self.target}
        if self.series is not None:
            out["series"] = self.series
        if self.threshold_s is not None:
            out["threshold_s"] = self.threshold_s
        return out

    # ------------------------------------------------------------- counting
    def events(self, store, source, window_s):
        """(bad, total) events for this objective over ``window_s`` of
        ``source``'s rings."""
        if self.kind == "availability":
            bad = store.counter_delta(
                "%s.counter.errors" % source, window_s)
            good = store.counter_delta(
                "%s.counter.responses" % source, window_s)
            return bad, bad + good
        if self.kind == "shed_rate":
            bad = (store.counter_delta("%s.counter.shed" % source,
                                       window_s)
                   + store.counter_delta(
                       "%s.counter.rejected" % source, window_s))
            total = bad + store.counter_delta(
                "%s.counter.responses" % source, window_s)
            return bad, total
        good, total = store.count_in_window(
            "%s.hist.%s" % (source, self.series), window_s,
            self.threshold_s)
        return total - good, total


#: state machine values (the ``slo_state{objective=}`` gauge)
OK, WARN, PAGE = 0, 1, 2
STATE_NAMES = {OK: "ok", WARN: "warn", PAGE: "page"}


class SLOMonitor(Logger):
    """Evaluate ``objectives`` over ``store`` (a TimeSeriesStore) per
    source; see the module docstring.  ``sources`` defaults to every
    source the store samples; ``checker`` attaches the PR 10
    HealthChecker page hook (``source_replicas`` maps source key →
    replica index — built automatically by ``serve_lm``)."""

    #: lock-discipline map (ISSUE 15): the state machine advances on
    #: the sampler thread while ``/slo.json`` snapshots read from
    #: handlers — state and last-eval rows move together under one
    #: lock.
    _guarded_by = {
        "_state": "_lock",
        "_last": "_lock",
        "evaluations": "_lock",
    }

    def __init__(self, store, objectives, windows_s=(60.0, 300.0),
                 warn_burn=1.0, page_burn=2.0, min_events=5,
                 sources=None, checker=None, source_replicas=None,
                 metrics=None, name="slo"):
        if not objectives:
            raise ValueError("need at least one objective")
        windows_s = tuple(sorted(float(w) for w in windows_s))
        if not windows_s or windows_s[0] <= 0:
            raise ValueError("windows_s must be positive")
        self.name = name
        self.store = store
        self.objectives = list(objectives)
        self.windows_s = windows_s
        self.warn_burn = float(warn_burn)
        self.page_burn = float(page_burn)
        self.min_events = int(min_events)
        self._sources = list(sources) if sources is not None else None
        self.checker = checker
        self.source_replicas = dict(source_replicas or {})
        self.metrics = metrics or ServingMetrics(name)
        self._lock = lockcheck.make_lock("slo._lock")
        #: (source, objective) -> state
        self._state = {}
        self._last = {}          # (source, objective) -> last eval row
        self.evaluations = 0

    # ---------------------------------------------------------- construction
    @classmethod
    def from_spec(cls, spec, store, **kw):
        """Build from a JSON file path, a parsed dict, a list of
        objective dicts, or pass an SLOMonitor through.  None/False →
        None."""
        if spec is None or spec is False:
            return None
        if isinstance(spec, SLOMonitor):
            return spec
        if isinstance(spec, str):
            with open(spec, "r", encoding="utf-8") as f:
                spec = json.load(f)
        if isinstance(spec, (list, tuple)):
            spec = {"objectives": list(spec)}
        if not isinstance(spec, dict) or "objectives" not in spec:
            raise ValueError(
                "SLO spec must be a JSON object with 'objectives' "
                "(or a list of objectives); got %r" % (spec,))
        objectives = [o if isinstance(o, Objective)
                      else Objective.from_dict(o)
                      for o in spec["objectives"]]
        for key in ("windows_s", "warn_burn", "page_burn",
                    "min_events"):
            if key in spec and key not in kw:
                kw[key] = spec[key]
        return cls(store, objectives, **kw)

    @staticmethod
    def default_objectives():
        """The stock objective set ``serve_lm(slo=True)`` arms:
        availability 99.9%, TTFT p95 < 1 s, decode-step p99 < 250 ms,
        shed under 1%% — deliberately loose defaults meant to catch
        fires, not tune latency; ship a file for real targets."""
        return [
            Objective("availability", "availability", 0.999),
            Objective("ttft", "latency", 0.95, series="ttft",
                      threshold_s=1.0),
            Objective("decode_step", "latency", 0.99,
                      series="decode_step", threshold_s=0.25),
            Objective("shed", "shed_rate", 0.99),
        ]

    # ------------------------------------------------------------ evaluation
    def _eval_sources(self):
        if self._sources is not None:
            return list(self._sources)
        return self.store.sources()

    def sample_once(self):
        """One synchronous evaluation of every (source, objective)
        pair; returns the rows.  Registered as the store's post-tick
        listener by ``serve_lm`` (and driven by hand in tests/chaos),
        so state advances once per sampling window."""
        rows = []
        paged = {}               # source -> [objective names], FRESH
        held = set()             # sources with any held (stale) row
        sources = self._eval_sources()
        for src in sources:
            for obj in self.objectives:
                row = self._eval_one(src, obj)
                rows.append(row)
                if row["state"] == PAGE:
                    if row["held"]:
                        # a PAGE carried by the min_events gate is
                        # STALE evidence (a quarantined replica serves
                        # no traffic, so its window never refills) —
                        # display it, but never re-feed the checker
                        # from it: that would re-quarantine a
                        # recovered replica forever on the same burst
                        held.add(src)
                    else:
                        paged.setdefault(src, []).append(obj.name)
                elif row["held"]:
                    held.add(src)
        with self._lock:
            self.evaluations += 1
        # the router hook: a FRESHLY-paging replica source counts
        # toward the checker's fail_threshold on its DEDICATED counter
        # — only when it is NOT the whole fleet burning (quarantining
        # every replica is an outage, not a mitigation), which also
        # keeps a solo engine un-quarantined.  Sources whose every row
        # is fresh and not paging clear their streak, so the threshold
        # means CONSECUTIVE scans of live page evidence; held (stale)
        # sources touch the streak in neither direction.
        if self.checker is not None:
            mapped = [s for s in sources if s in self.source_replicas]
            burning = [s for s in paged if s in self.source_replicas]
            feed = bool(burning) and len(burning) < len(mapped)
            for src in mapped:
                if feed and src in paged:
                    self.checker.note_slo_page(
                        self.source_replicas[src],
                        reason="slo page: %s" % ",".join(paged[src]))
                elif src not in paged and src not in held:
                    self.checker.note_slo_ok(self.source_replicas[src])
        return rows

    #: synonym — the convention every driveable loop in serving uses
    step = sample_once

    def _eval_one(self, source, obj):
        key = (source, obj.name)
        with self._lock:
            prev = self._state.get(key, OK)
        burns = {}
        short_events = None
        for w in self.windows_s:
            bad, total = obj.events(self.store, source, w)
            ratio = bad / total if total else 0.0
            burns[w] = {"window_s": w, "bad": bad, "events": total,
                        "error_ratio": round(ratio, 6),
                        "burn": round(ratio / obj.budget, 4)}
            if short_events is None:
                short_events = total
        short = burns[self.windows_s[0]]["burn"]
        hold = short_events < self.min_events
        if hold:
            state = prev             # too little evidence to move
        elif short < self.warn_burn:
            state = OK
        elif all(b["burn"] >= self.page_burn
                 for b in burns.values()):
            state = PAGE
        else:
            state = WARN
        if state != prev:
            self._transition(source, obj, prev, state)
        row = {"source": source, "objective": obj.name,
               "kind": obj.kind, "target": obj.target,
               "state": state, "state_name": STATE_NAMES[state],
               "held": hold,
               "burn_rates": list(burns.values()),
               "budget": round(obj.budget, 6)}
        if obj.threshold_s is not None:
            row["threshold_s"] = obj.threshold_s
        if obj.series is not None:
            # consumers (tools/slo_report.py) replay the named
            # histogram — a latency objective's series must round-trip
            row["series"] = obj.series
        with self._lock:
            # the sampler thread evaluates while /slo.json snapshots
            # read — state and rows move together under the lock so a
            # reader never iterates a dict mid-insert
            self._state[key] = state
            self._last[key] = row
        return row

    def _transition(self, source, obj, prev, state):
        self.metrics.set_gauge(
            "slo_state", state,
            labels={"objective": obj.name, "source": source})
        if state == PAGE:
            self.metrics.inc("slo_pages_total")
            self.warning("SLO PAGE: %s/%s burning past %.1fx on every "
                         "window", source, obj.name, self.page_burn)
        elif state == WARN and prev == OK:
            self.metrics.inc("slo_warns_total")
            self.info("SLO warn: %s/%s short-window burn >= %.1fx",
                      source, obj.name, self.warn_burn)
        elif state == OK:
            self.metrics.inc("slo_recoveries_total")
            self.info("SLO recovered: %s/%s back under budget",
                      source, obj.name)

    # --------------------------------------------------------------- reading
    def states(self):
        """(source, objective) -> state (the gauge's source of
        truth)."""
        with self._lock:
            return dict(self._state)

    def state(self, source, objective):
        with self._lock:
            return self._state.get((source, objective), OK)

    def worst_state(self):
        with self._lock:
            return max(self._state.values(), default=OK)

    def snapshot(self):
        """The ``GET /slo.json`` payload — strict JSON, shared
        monotonic ``sampled_at`` stamp."""
        with self._lock:
            evaluations = self.evaluations
            rows = [dict(v) for v in self._last.values()]
        return {"name": self.name,
                "sampled_at": round(monotonic_offset(), 6),
                "windows_s": list(self.windows_s),
                "warn_burn": self.warn_burn,
                "page_burn": self.page_burn,
                "min_events": self.min_events,
                "evaluations": evaluations,
                "worst_state": self.worst_state(),
                "worst_state_name": STATE_NAMES[self.worst_state()],
                "pages_total": self.metrics.counter("slo_pages_total"),
                "objectives": rows}
