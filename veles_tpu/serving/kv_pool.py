"""Paged KV-cache allocator — host-side page bookkeeping (ISSUE 6).

The memory half of the paged serving refactor: device KV storage is ONE
pool of fixed-size pages per transformer block (``(n_pages, kv_heads,
page, head_dim)``, see ``ops/attention.py::paged_view``), and THIS
class decides which lane (or prefix-cache entry) owns which page.  All
state is host-side integers — allocation never touches the device, so
a prefix-cache hit that installs page REFERENCES into a lane's page
table is zero-copy and zero-dispatch by construction (the contiguous
path's row-copy install, docs/PERF.md's "correctness crutch", simply
has no paged equivalent to pay).

Three invariants the engine leans on:

- REF-COUNTED sharing: a page lives until its last referent (lanes
  and/or the radix prefix cache) releases it; ``alloc`` never hands
  out a page with live references, so one lane's decode can never
  scribble on rows another lane still attends.
- PINS mark in-flight use: a lane pins every page in its table while
  active.  Pins don't keep a page alive (refs do) — they make
  "eviction" (the trie dropping its reference under pool pressure)
  refuse pages a lane still reads, and releasing a still-pinned page
  is an engine bug this class turns into a loud error instead of a
  silent use-after-free.
- COPY-ON-WRITE discipline: writers must own their page exclusively.
  :meth:`shared` is the check; the engine's write paths consult it and
  copy the page (``_page_copy_jit``) before appending — the OTHER
  referents keep the original rows bit-identical.

Single-threaded by design: every call happens on the engine worker
thread (the same discipline as :class:`RadixPrefixCache`), so there is
no lock to contend on the per-token path.
"""

from __future__ import annotations

import collections


class KVPagePool:
    """Allocator over page ids ``1..num_pages`` (id 0 is the reserved
    SCRATCH page: free lanes park their page tables on it and warmup
    writes land there — it is never allocated, so its garbage content
    is never attended by a live mask)."""

    SCRATCH = 0

    #: ISSUE 15 annotation: the allocator is deliberately lock-free —
    #: every mutation happens on the engine worker thread (the engine
    #: lock is the module docstring's "single-threaded by design"
    #: rule), so the per-token path pays no contention.  checkpoint()
    #: documents the torn-read consequence for its best-effort reads.
    _synchronized_externally = "LMEngine worker thread (single owner)"

    def __init__(self, num_pages, page_size):
        if num_pages < 1:
            raise ValueError("kv pool needs at least one page")
        if page_size < 1:
            raise ValueError("page size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._refs = [0] * (self.num_pages + 1)
        self._pins = [0] * (self.num_pages + 1)
        self._free = collections.deque(range(1, self.num_pages + 1))

    # ------------------------------------------------------------ queries
    @property
    def free_pages(self):
        return len(self._free)

    @property
    def used_pages(self):
        return self.num_pages - len(self._free)

    @property
    def pinned_pages(self):
        """Pages held by an active lane (the gauge /metrics exposes)."""
        return sum(1 for p in self._pins[1:] if p > 0)

    @property
    def occupancy(self):
        """Used fraction of the pool (0..1) — the resident-KV pressure
        signal behind the ``kv_pages_free``/``kv_pages_total`` gauges
        the serving router weighs when placing requests (ISSUE 8)."""
        return self.used_pages / float(self.num_pages)

    def refs(self, page):
        return self._refs[page]

    def snapshot(self):
        """JSON-safe copy of the full allocator bookkeeping — what
        ``LMEngine.checkpoint`` (ISSUE 10) embeds so a crash leaves a
        post-mortem record of who owned what."""
        return {"num_pages": self.num_pages,
                "page_size": self.page_size,
                "refs": list(self._refs),
                "pins": list(self._pins),
                "free": list(self._free)}

    def verify(self):
        """Self-consistency audit (ISSUE 10): the free list holds
        exactly the zero-ref pages (each once, never the scratch
        page), no negative counts, and no pinned page without a
        referent.  Raises RuntimeError naming the first violation;
        returns a summary dict when sound — the crash-recovery path
        runs this before re-admitting any work."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise RuntimeError("free list holds duplicate pages")
        if self.SCRATCH in free:
            raise RuntimeError("scratch page entered the free list")
        for p in range(1, self.num_pages + 1):
            refs, pins = self._refs[p], self._pins[p]
            if refs < 0 or pins < 0:
                raise RuntimeError(
                    "page %d has negative bookkeeping (refs=%d, "
                    "pins=%d)" % (p, refs, pins))
            if (refs == 0) != (p in free):
                raise RuntimeError(
                    "page %d refs=%d but free-list membership is %s "
                    "— leaked or double-freed" % (p, refs, p in free))
            if pins and not refs:
                raise RuntimeError(
                    "page %d pinned (%d) with no referent" % (p, pins))
        return {"free": len(free), "used": self.used_pages,
                "pinned": self.pinned_pages}

    def shared(self, page):
        """True when appending into ``page`` needs copy-on-write."""
        return self._refs[page] > 1

    # --------------------------------------------------------- allocation
    def alloc(self, n=1):
        """Take ``n`` pages (refs=1 each) — ALL-OR-NOTHING: returns the
        page-id list, or None leaving the pool untouched when fewer
        than ``n`` are free (the engine then presses the prefix cache
        for evictions or requeues the request; partial grants would
        strand pages on a request that cannot run)."""
        if n < 0:
            raise ValueError("alloc(%d)" % n)
        if len(self._free) < n:
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def retain(self, page):
        """One more referent (a sharing lane, or the prefix cache)."""
        if not 1 <= page <= self.num_pages or self._refs[page] < 1:
            raise RuntimeError("retain of unallocated page %d" % page)
        self._refs[page] += 1

    def release(self, page):
        """Drop one reference; the page returns to the free list at
        zero.  Returns True when this release freed it.  Releasing an
        unallocated page, or freeing one that is still PINNED, is an
        engine bug — fail loudly, never recycle rows a lane reads."""
        if not 1 <= page <= self.num_pages or self._refs[page] < 1:
            raise RuntimeError("release of unallocated page %d" % page)
        self._refs[page] -= 1
        if self._refs[page] == 0:
            if self._pins[page]:
                self._refs[page] += 1
                raise RuntimeError(
                    "page %d freed while still pinned by a lane" % page)
            self._free.append(page)
            return True
        return False

    # --------------------------------------------------------------- pins
    def pin(self, page):
        if not 1 <= page <= self.num_pages or self._refs[page] < 1:
            raise RuntimeError("pin of unallocated page %d" % page)
        self._pins[page] += 1

    def unpin(self, page):
        if self._pins[page] < 1:
            raise RuntimeError("unpin of unpinned page %d" % page)
        self._pins[page] -= 1

    def pinned(self, page):
        return self._pins[page] > 0
