"""End-to-end request tracing for the serving stack (ISSUE 12).

The serving tier's metrics (``serving/metrics.py``) answer aggregate
questions — p95 latency, dispatch counts, EWMAs — but not "where did
THIS request's 400 ms go?".  This module adds a lock-cheap SPAN TRACER
threaded through the whole request path: ``restful_api.py`` opens an
``http.request`` root span, ``serving/router.py`` records one child
span per placement ATTEMPT (retries, hedges and drains included),
``serving/batcher.py`` and ``serving/lm_engine.py`` record queue wait,
admission, every prefill chunk, every decode/verify dispatch, COW page
copies and weight-swap applies.  A fused decode megastep (ISSUE 13)
records ONE ``decode.megastep`` span per K-token dispatch — shared
dispatch id, per-lane tokens-emitted on each request's copy — so the
cost ledger counts the fused program once, never the folded per-token
work.  Spans carry the request id, replica,
weights_version and fast-path attributes (bucket, live width, backend),
so a single request's timeline reads end to end across threads and
engines.

Design rules (the ``faults.py`` discipline):

- UNARMED IS FREE.  Engines hold ``self._tracer = None`` by default and
  every site is one attribute-is-None check — no lock, no allocation.
  The chaos bench's overhead leg pins the unarmed cost inside the same
  <2% bound as the fault layer.
- DEVICE SPANS ARE FENCED.  jit dispatch is asynchronous — a span that
  closed at dispatch-return would measure enqueue, not execution.  When
  (and only when) tracing is armed, each dispatch site calls
  ``jax.block_until_ready`` on its outputs before closing the span, so
  durations are device wall time.  That sync is the documented cost of
  ARMED tracing; unarmed engines never fence.
- THE FLIGHT RECORDER IS BOUNDED.  Finished requests land in a ring
  buffer (``last`` requests), so the recent past is always
  reconstructable after the fact; a request that errors or blows its
  deadline is additionally DUMPED (waterfall text, kept in a second
  small ring and logged) the moment it finishes — post-mortems need no
  foresight.
- ONE DISPATCH, ONE COST.  A batched decode tick serves many lanes; the
  tracer records the span once per PARTICIPATING request (each request's
  timeline is complete) but stamps every copy with a shared dispatch id
  (``did``) so the COST LEDGER counts the dispatch once.

Modes (``serve_lm(trace=)`` / ``--serve-trace``):

=============== ======================================================
``off``         no tracer (the default — zero overhead)
``all``         every request traced and retained in the ring
``sample:P``    a seeded coin traces fraction P of requests
``errors``      every request traced, but only errored/deadline-blown
                requests are RETAINED (the ring holds exactly the
                post-mortem set)
=============== ======================================================

Consumers: ``GET /trace.json?last=N`` exports the ring as
Chrome-trace/Perfetto JSON (load at https://ui.perfetto.dev or
chrome://tracing — one track per request), and ``tools/trace_report.py``
renders per-request waterfalls and aggregates spans into the per-op
cost ledger (op family x bucket x backend -> p50/p95 duration, dispatch
count) that the ROADMAP's cost-model autotuning item needs.

Context plumbing: the REQUEST context travels two ways.  Down a call
stack, :func:`use` binds a :class:`TraceContext` to the thread and
:func:`current` reads it back (HTTP handler -> router -> engine submit
all run on the caller's thread).  Across threads, the context rides the
request object itself (``_Request.trace``), so the engine worker
thread attributes its dispatch spans to the right requests.  Whoever
STARTED a request's trace finishes it (``TraceContext.owns``); layers
below only add child spans.
"""

from __future__ import annotations

import collections
import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck

_tls = threading.local()


def current():
    """The calling thread's active :class:`TraceContext` (bound by
    :func:`use`), :data:`SAMPLED_OUT`, or None — how a lower serving
    layer (router, engine, batcher) joins the request its caller
    already started instead of rooting a second one."""
    return getattr(_tls, "ctx", None)


#: sentinel an outer layer binds (via :func:`use`) when ITS sampler
#: skipped the request: lower layers must not re-roll the coin —
#: without this, ``sample:P`` behind HTTP would trace ~1-(1-P)^3 of
#: traffic as partial router-/engine-rooted trees
SAMPLED_OUT = object()


def join_or_root(tracer, name, cat="request", attrs=None):
    """THE join-or-root decision every traced layer makes on its
    submit path: returns ``(ctx, own_root)`` where ``ctx`` is the
    caller's existing context (own_root False), a fresh root this
    layer now OWNS (own_root True — it must ``finish_request``), or
    :data:`SAMPLED_OUT` when the sampler — here or upstream — skipped
    the request (record nothing, but PROPAGATE the sentinel to layers
    below via :func:`use`)."""
    up = current()
    if up is not None:          # a real ctx OR the sentinel
        return up, False
    ctx = tracer.start_request(name=name, cat=cat, attrs=attrs)
    if ctx is None:
        return SAMPLED_OUT, False
    return ctx, True


class use:
    """Bind ``ctx`` as the thread's current trace context for a
    ``with`` block (restored on exit, exception or not)."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


class TraceContext:
    """One traced request's handle: the owning tracer, the request id,
    the root span and the parent span new child spans attach under.
    ``at(sid)`` derives a context parented at ``sid`` (the router hands
    the engine a context under the current ATTEMPT span, so engine
    spans nest per attempt).  ``owns`` marks the layer that must call
    :meth:`SpanTracer.finish_request`."""

    __slots__ = ("tracer", "rid", "root", "parent", "owns")

    def __init__(self, tracer, rid, root, parent=None, owns=False):
        self.tracer = tracer
        self.rid = rid
        self.root = root
        self.parent = parent if parent is not None else root
        self.owns = owns

    def at(self, sid):
        return TraceContext(self.tracer, self.rid, self.root,
                            parent=sid, owns=False)


class _Span:
    __slots__ = ("sid", "parent", "name", "cat", "t0", "t1", "attrs")

    def __init__(self, sid, parent, name, cat, t0, t1=None, attrs=None):
        self.sid = sid
        self.parent = parent
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs


class SpanTracer(Logger):
    """The serving stack's span recorder; see the module docstring.

    Thread-safe: every mutation is a few dict/list operations under one
    lock.  ``last`` bounds the flight recorder (finished requests),
    ``max_spans`` bounds any single request's span count (a runaway
    long decode cannot grow without bound — excess spans are counted,
    not stored), ``seed`` makes ``sample:P`` reproducible."""

    MODES = ("all", "errors", "sample")

    #: lock-discipline map (ISSUE 15): the span store is mutated from
    #: every serving thread (handlers, workers, timers) — all of it
    #: under the one tracer lock, including the seeded sampler RNG.
    _guarded_by = {
        "_sid": "_lock", "_did": "_lock", "_auto_rid": "_lock",
        "_live": "_lock", "_ring": "_lock", "_dumps": "_lock",
        "_events": "_lock", "_ledger_live": "_lock", "_rng": "_lock",
        "started": "_lock", "finished": "_lock",
        "sampled_out": "_lock", "dropped_spans": "_lock",
        "dump_count": "_lock",
    }

    def __init__(self, mode="all", sample=1.0, last=64, max_spans=4096,
                 seed=0, name="trace", clock=time.monotonic):
        if mode not in self.MODES:
            raise ValueError("trace mode %r (one of %r)"
                             % (mode, self.MODES))
        self.name = name
        self.mode = mode
        self.sample = float(sample)
        self.max_spans = int(max_spans)
        self._clock = clock
        self._origin = clock()
        self._lock = lockcheck.make_lock("tracing._lock")
        self._rng = numpy.random.RandomState(seed)
        self._sid = 0
        self._did = 0
        self._auto_rid = 0
        self._live = {}                  # rid -> building record
        self._ring = collections.deque(maxlen=int(last))
        self._dumps = collections.deque(maxlen=32)
        #: engine-scope spans with no request (weight-swap applies,
        #: router drains/deploys) — exported on their own track
        self._events = collections.deque(maxlen=512)
        #: the LIVE per-op cost ledger (ISSUE 14): maintained
        #: incrementally as device spans are recorded — same rows, same
        #: dedup-by-dispatch-id rule as :func:`cost_ledger` over the
        #: ring (asserted equal on the same trace), but O(1) to serve
        #: (``GET /ledger.json``) and unbounded in TIME: it survives
        #: ring eviction and errors-mode discards.  Memory stays
        #: bounded: exact dispatch/lane counts, quantiles over the
        #: newest ``ledger_durs`` dispatch durations per row.
        self.ledger_durs = 2048
        self._ledger_live = {}           # key -> {durs, lanes, n}
        self.started = 0
        self.finished = 0
        self.sampled_out = 0
        self.dropped_spans = 0
        self.dump_count = 0

    @classmethod
    def from_spec(cls, spec, **kw):
        """Build a tracer from the CLI/`serve_lm(trace=)` spec:
        ``None``/``False``/``0``/``'off'`` -> None (tracing disabled),
        ``True``/``'all'``/``'errors'`` -> that mode, ``'sample:P'``
        -> seeded sampling at probability P, an existing
        :class:`SpanTracer` passes through."""
        if spec is None or spec is False or spec == 0 or spec == "off":
            return None
        if isinstance(spec, SpanTracer):
            return spec
        if spec is True:
            return cls(mode="all", **kw)
        s = str(spec)
        if s.startswith("sample:"):
            return cls(mode="sample", sample=float(s.split(":", 1)[1]),
                       **kw)
        if s in ("all", "errors"):
            return cls(mode=s, **kw)
        raise ValueError(
            "trace spec %r (off|errors|all|sample:P or a SpanTracer)"
            % (spec,))

    def _now(self):
        return self._clock() - self._origin

    # ------------------------------------------------------------ recording
    def start_request(self, rid=None, name="request", cat="request",
                      attrs=None):
        """Open a request's trace; returns its (owning)
        :class:`TraceContext`, or None when the sampler skipped it —
        callers treat None exactly like tracing-off.  ``rid`` is the
        join key across layers (the HTTP ``X-Request-Id``); omitted,
        one is generated."""
        with self._lock:
            self.started += 1
            if self.mode == "sample" \
                    and self._rng.random_sample() >= self.sample:
                self.sampled_out += 1
                return None
            if rid is None:
                self._auto_rid += 1
                rid = "r%05d" % self._auto_rid
            rid = str(rid)
            if rid in self._live:       # client-reused id: keep both
                self._auto_rid += 1
                rid = "%s#%d" % (rid, self._auto_rid)
            self._sid += 1
            sid = self._sid
            self._live[rid] = {
                "rid": rid,
                "spans": {sid: _Span(sid, None, name, cat,
                                     self._now(), attrs=attrs)},
                "open": {sid},
                "root": sid,
            }
        return TraceContext(self, rid, sid, owns=True)

    def begin(self, ctx, name, cat="span", attrs=None, parent=None):
        """Open a child span under ``ctx``; returns an opaque handle
        for :meth:`end` (None when the request is gone or at its span
        cap — safe to pass back to ``end``)."""
        if ctx is None:
            return None
        with self._lock:
            rec = self._live.get(ctx.rid)
            if rec is None:
                return None
            if len(rec["spans"]) >= self.max_spans:
                self.dropped_spans += 1
                return None
            self._sid += 1
            sid = self._sid
            rec["spans"][sid] = _Span(
                sid, parent if parent is not None else ctx.parent,
                name, cat, self._now(), attrs=attrs)
            rec["open"].add(sid)
        return (ctx.rid, sid)

    def end(self, handle, attrs=None, error=None):
        """Close a span (idempotent: a handle already closed — or None
        — is a no-op, so racing completion paths cannot corrupt a
        timeline)."""
        if handle is None:
            return
        rid, sid = handle
        t1 = self._now()
        with self._lock:
            rec = self._live.get(rid)
            if rec is None:
                return
            span = rec["spans"].get(sid)
            if span is None or span.t1 is not None:
                return
            span.t1 = t1
            rec["open"].discard(sid)
            if attrs:
                span.attrs = dict(span.attrs or (), **attrs)
            if error is not None:
                span.attrs = dict(span.attrs or (),
                                  error=_err_str(error))

    def instant(self, ctx, name, cat="mark", attrs=None):
        """A zero-duration marker span (retry scheduled, prefix hit,
        swap requeue, ...)."""
        if ctx is None:
            return
        t = self._now()
        with self._lock:
            rec = self._live.get(ctx.rid)
            if rec is None or len(rec["spans"]) >= self.max_spans:
                return
            self._sid += 1
            rec["spans"][self._sid] = _Span(
                self._sid, ctx.parent, name, cat, t, t, attrs)

    def add_many(self, ctxs, name, cat, t0, t1, attrs=None,
                 each_attrs=None):
        """Record one COMPLETED span per context — the batched-dispatch
        path (one decode tick advances many lanes): each participating
        request's timeline gets the span, all copies share one
        dispatch id (``did``) so the cost ledger counts the device
        dispatch once.  ``t0``/``t1`` are raw clock readings
        (``time.monotonic()`` — the caller already timed the fenced
        dispatch).  ``each_attrs`` (same length as ``ctxs``) merges
        per-participant attributes into that context's copy ON TOP of
        the shared ``attrs`` — the decode megastep (ISSUE 13) stamps
        each lane's own tokens-emitted count on a span the ledger
        still counts once.  Returns the did (None when nothing
        recorded)."""
        did = None
        recorded = 0
        t0 -= self._origin
        t1 -= self._origin
        with self._lock:
            for i, ctx in enumerate(ctxs):
                if ctx is None:
                    continue
                rec = self._live.get(ctx.rid)
                if rec is None:
                    continue
                if len(rec["spans"]) >= self.max_spans:
                    self.dropped_spans += 1
                    continue
                if did is None:
                    self._did += 1
                    did = self._did
                span_attrs = dict(attrs or (), did=did)
                if each_attrs is not None and each_attrs[i]:
                    span_attrs.update(each_attrs[i])
                self._sid += 1
                rec["spans"][self._sid] = _Span(
                    self._sid, ctx.parent, name, cat, t0, t1,
                    span_attrs)
                recorded += 1
            if recorded:
                self._ledger_note(name, attrs, t0, t1, recorded)
        return did

    def add(self, ctx, name, cat, t0, t1, attrs=None):
        """One completed span on one request (unbatched dispatches)."""
        return self.add_many((ctx,), name, cat, t0, t1, attrs)

    def _ledger_note(self, name, attrs, t0, t1, lanes):
        # caller-holds: _lock
        """Fold one recorded dispatch into the live cost ledger
        (tracer lock held).  Mirrors :func:`cost_ledger` exactly: only
        device spans (a ``backend`` attr) count, one duration per
        dispatch id (this call), ``lanes`` per recorded span copy.
        Cost: one dict lookup + a deque append — measured and bounded
        (with the telemetry sampler) by the chaos overhead leg."""
        backend = (attrs or {}).get("backend") if attrs else None
        if backend is None:
            return
        key = (name, str((attrs or {}).get("bucket", "-")),
               str(backend))
        row = self._ledger_live.get(key)
        if row is None:
            row = self._ledger_live[key] = {
                "durs": collections.deque(maxlen=self.ledger_durs),
                "lanes": 0, "dispatches": 0}
        row["durs"].append(max(0.0, t1 - t0) * 1e3)
        row["lanes"] += lanes
        row["dispatches"] += 1

    def live_ledger(self):
        """The incrementally-maintained per-op cost ledger — the same
        row shape (and, while nothing has aged past the ring or the
        per-row duration window, the same values) as
        :func:`cost_ledger` over this tracer's records, served without
        touching the flight recorder.  ``dispatches``/``lanes`` are
        exact lifetime counts; p50/p95/mean/total cover the newest
        ``ledger_durs`` dispatches per row."""
        with self._lock:
            table = {key: {"durs": list(row["durs"]),
                           "lanes": row["lanes"],
                           "dispatches": row["dispatches"]}
                     for key, row in self._ledger_live.items()}
        return _ledger_rows(table)

    def event(self, name, cat="engine", t0=None, t1=None, attrs=None):
        """An ENGINE-scope span with no owning request (weight-swap
        apply, router drain/deploy) — bounded side channel, exported on
        its own track, excluded from per-request tree checks.
        ``t0``/``t1`` are raw clock readings (``time.monotonic()``);
        omitted, the event is an instant at now."""
        now = self._now()
        t0 = now if t0 is None else t0 - self._origin
        t1 = now if t1 is None else t1 - self._origin
        with self._lock:
            self._events.append({
                "name": name, "cat": cat, "t0": t0, "t1": t1,
                "attrs": dict(attrs or ())})

    def finish_request(self, ctx, error=None, deadline=False,
                       attrs=None):
        """Close a request's trace: the root (and any span a fault path
        left open — flagged ``unclosed``) is ended, the record moves to
        the flight-recorder ring (mode ``errors`` retains only
        errored/deadline requests), and an errored or deadline-blown
        request is DUMPED (waterfall text logged + kept).  Idempotent —
        racing finishers (a timed-out caller and a late worker) cannot
        double-record.  Returns the finished record, or None when the
        request was already finished or discarded by ``errors``-mode
        retention."""
        rid = ctx.rid if isinstance(ctx, TraceContext) else str(ctx)
        t1 = self._now()
        dump = error is not None or deadline
        keep = self.mode != "errors" or dump
        with self._lock:
            rec = self._live.pop(rid, None)
            if rec is None:
                return None
            self.finished += 1
            if not keep:
                # errors-mode discard: no O(spans) record build under
                # the lock for the (common) successful case — the armed
                # decode hot path shares this lock
                return None
            unclosed = []
            root = rec["root"]
            for sid in rec["open"]:
                span = rec["spans"][sid]
                span.t1 = t1
                if sid != root:
                    span.attrs = dict(span.attrs or (), unclosed=True)
                    unclosed.append(span.name)
            if attrs:
                rspan = rec["spans"][root]
                rspan.attrs = dict(rspan.attrs or (), **attrs)
            out = {
                "rid": rid,
                "error": _err_str(error) if error is not None else None,
                "deadline_blown": bool(deadline),
                "unclosed": unclosed,
                "spans": [{"sid": s.sid, "parent": s.parent,
                           "name": s.name, "cat": s.cat,
                           "t0": s.t0, "t1": s.t1,
                           "attrs": dict(s.attrs) if s.attrs else {}}
                          for s in rec["spans"].values()],
            }
            self._ring.append(out)
            if dump:
                self.dump_count += 1
        if dump:
            # render OUTSIDE the lock: the waterfall is O(spans) string
            # work, and an error burst must not stall the armed trace
            # sites (add_many on the decode hot path) behind it
            text = format_waterfall(out)
            with self._lock:
                self._dumps.append({"rid": rid, "text": text})
            self.warning("flight recorder dump (%s):\n%s",
                         "deadline" if deadline and error is None
                         else "error", text)
        return out

    # -------------------------------------------------------------- reading
    def requests(self, last=None):
        """The flight recorder's finished requests, oldest first
        (``last`` trims to the newest N)."""
        with self._lock:
            out = list(self._ring)
        if last is not None:
            last = int(last)
            out = out[-last:] if last > 0 else []
        return out

    def find(self, rid):
        """The NEWEST finished record for ``rid`` — the after-the-fact
        reconstruction path ("what happened to request X?")."""
        with self._lock:
            for rec in reversed(self._ring):
                if rec["rid"] == rid:
                    return rec
        return None

    def dumps(self):
        """Auto-dumped waterfalls ({"rid", "text"}), newest last."""
        with self._lock:
            return list(self._dumps)

    def stats(self):
        with self._lock:
            return {"mode": self.mode, "started": self.started,
                    "finished": self.finished,
                    "sampled_out": self.sampled_out,
                    "live": len(self._live),
                    "retained": len(self._ring),
                    "dropped_spans": self.dropped_spans,
                    "dumps": self.dump_count}

    def export_chrome(self, last=None):
        """The ring (newest ``last`` requests) + engine events as a
        Chrome-trace/Perfetto JSON object — one track (tid) per
        request, engine events on tid 0, ts/dur in microseconds.  Load
        at https://ui.perfetto.dev or chrome://tracing."""
        recs = self.requests(last)
        with self._lock:
            events = list(self._events)
        out = [{"ph": "M", "pid": 1, "tid": 0, "name": "thread_name",
                "args": {"name": "engine events"}}]
        for ev in events:
            out.append({"ph": "X", "pid": 1, "tid": 0,
                        "name": ev["name"], "cat": ev["cat"],
                        "ts": round(ev["t0"] * 1e6, 1),
                        "dur": round(max(0.0, ev["t1"] - ev["t0"])
                                     * 1e6, 1),
                        "args": ev["attrs"]})
        for tid, rec in enumerate(recs, start=1):
            label = "req %s" % rec["rid"]
            if rec["error"]:
                label += " [ERROR]"
            elif rec["deadline_blown"]:
                label += " [DEADLINE]"
            # rid/error/deadline ride as structured args too — the
            # label is for humans, and a rid containing spaces must
            # not confuse trace_report's rebuild
            out.append({"ph": "M", "pid": 1, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": label, "rid": rec["rid"],
                                 "error": rec["error"],
                                 "deadline_blown":
                                     rec["deadline_blown"]}})
            for sp in rec["spans"]:
                args = dict(sp["attrs"], rid=rec["rid"],
                            sid=sp["sid"], parent=sp["parent"])
                out.append({"ph": "X", "pid": 1, "tid": tid,
                            "name": sp["name"], "cat": sp["cat"],
                            "ts": round(sp["t0"] * 1e6, 1),
                            "dur": round(max(0.0, (sp["t1"] or sp["t0"])
                                         - sp["t0"]) * 1e6, 1),
                            "args": args})
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "otherData": {"tracer": self.name, "mode": self.mode,
                              "stats": self.stats()}}

    def ledger(self, last=None):
        """The per-op cost ledger over the flight recorder — see
        :func:`cost_ledger`."""
        return cost_ledger(self.requests(last))


def _err_str(error):
    if isinstance(error, BaseException):
        return "%s: %s" % (type(error).__name__, error)
    return str(error)


def finish_from_future(ctx, future):
    """Future-settlement hook for engine-/router-owned roots: finish
    the request's trace with the future's outcome (result, exception —
    deadline sheds flagged — or cancellation)."""
    error, deadline = None, False
    if future.cancelled():
        error = "cancelled"
    else:
        exc = future.exception()
        if exc is not None:
            error = exc
            from veles_tpu.serving.batcher import DeadlineExceeded
            deadline = isinstance(exc, DeadlineExceeded)
    ctx.tracer.finish_request(ctx, error=error, deadline=deadline)


def verify_integrity(records):
    """Assert every finished request's span tree is sound: exactly one
    root (parent None), every parent resolves INSIDE the same request,
    every span closed with t1 >= t0, nothing flagged ``unclosed``.
    Raises AssertionError naming the first violation; returns
    ``{"requests", "spans"}`` when clean — the bench/test contract
    (a traced run whose trees do not verify is a bug, not data)."""
    total = 0
    for rec in records:
        rid = rec["rid"]
        spans = rec["spans"]
        sids = {s["sid"] for s in spans}
        roots = [s for s in spans if s["parent"] is None]
        if len(roots) != 1:
            raise AssertionError(
                "request %s has %d root spans (want exactly 1): %r"
                % (rid, len(roots), [s["name"] for s in roots]))
        if rec["unclosed"]:
            raise AssertionError(
                "request %s finished with unclosed span(s): %r"
                % (rid, rec["unclosed"]))
        for s in spans:
            if s["parent"] is not None and s["parent"] not in sids:
                raise AssertionError(
                    "request %s span %s (sid %d) is an ORPHAN: parent "
                    "%d is not in this request"
                    % (rid, s["name"], s["sid"], s["parent"]))
            if s["t1"] is None:
                raise AssertionError(
                    "request %s span %s never closed" % (rid, s["name"]))
            if s["t1"] < s["t0"]:
                raise AssertionError(
                    "request %s span %s closed before it opened"
                    % (rid, s["name"]))
            if s["attrs"].get("unclosed"):
                raise AssertionError(
                    "request %s span %s flagged unclosed"
                    % (rid, s["name"]))
        total += len(spans)
    return {"requests": len(records), "spans": total}


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def cost_ledger(records):
    """Aggregate DEVICE spans (those stamped with a ``backend`` attr)
    into the per-op cost table the autotuning item needs: one row per
    (op family x bucket x backend) with dispatch count and p50/p95/mean
    duration (ms).  Batched spans are deduplicated by dispatch id, so
    ``dispatches`` counts device programs launched, not lanes served
    (``lanes`` keeps the participation count)."""
    table = {}
    seen = set()
    for rec in records:
        for sp in rec["spans"]:
            attrs = sp["attrs"]
            backend = attrs.get("backend")
            if backend is None:
                continue
            key = (sp["name"], str(attrs.get("bucket", "-")),
                   str(backend))
            row = table.setdefault(key, {"durs": [], "lanes": 0})
            row["lanes"] += 1
            did = attrs.get("did")
            if did is not None and (key, did) in seen:
                continue
            if did is not None:
                seen.add((key, did))
            row["durs"].append(
                max(0.0, (sp["t1"] or sp["t0"]) - sp["t0"]) * 1e3)
    return _ledger_rows(table)


def _ledger_rows(table):
    """``{(op, bucket, backend): {durs, lanes[, dispatches]}}`` into
    the sorted ledger-row list — ONE builder for :func:`cost_ledger`
    (record aggregation) and :meth:`SpanTracer.live_ledger` (the
    ISSUE 14 incremental ledger), so the two cannot drift in shape or
    rounding."""
    rows = []
    for (op, bucket, backend), row in table.items():
        durs = sorted(row["durs"])
        rows.append({
            "op": op, "bucket": bucket, "backend": backend,
            "dispatches": row.get("dispatches", len(durs)),
            "lanes": row["lanes"],
            "p50_ms": round(_pct(durs, 0.50), 4),
            "p95_ms": round(_pct(durs, 0.95), 4),
            "mean_ms": round(sum(durs) / len(durs), 4) if durs else 0.0,
            "total_ms": round(sum(durs), 3),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def format_waterfall(record, width=40):
    """One finished request as an indented ASCII waterfall — the
    flight-recorder dump format (and ``tools/trace_report.py``'s
    per-request view)."""
    spans = sorted(record["spans"], key=lambda s: (s["t0"], s["sid"]))
    if not spans:
        return "request %s: no spans" % record["rid"]
    t0 = min(s["t0"] for s in spans)
    t1 = max((s["t1"] if s["t1"] is not None else s["t0"])
             for s in spans)
    total = max(t1 - t0, 1e-9)
    depth = {}
    by_sid = {s["sid"]: s for s in spans}
    for s in spans:
        d, p = 0, s["parent"]
        while p is not None and p in by_sid:
            d += 1
            p = by_sid[p]["parent"]
        depth[s["sid"]] = d
    head = "request %s  (%.3f ms total%s%s)" % (
        record["rid"], total * 1e3,
        ", ERROR: %s" % record["error"] if record["error"] else "",
        ", DEADLINE BLOWN" if record["deadline_blown"] else "")
    lines = [head]
    for s in spans:
        end = s["t1"] if s["t1"] is not None else s["t0"]
        lo = int((s["t0"] - t0) / total * width)
        hi = max(lo + 1, int((end - t0) / total * width))
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        attrs = s["attrs"]
        extras = " ".join(
            "%s=%s" % (k, v) for k, v in sorted(attrs.items())
            if k not in ("did",))
        lines.append("  [%s] %8.3fms %s%s%s" % (
            bar, (end - s["t0"]) * 1e3, "  " * depth[s["sid"]],
            s["name"], (" {%s}" % extras) if extras else ""))
    return "\n".join(lines)
