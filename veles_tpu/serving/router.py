"""Data-parallel LM serving — N engine replicas behind a metrics-driven
router (ISSUE 8), hardened into a RESILIENCE layer (ISSUE 10).

Tensor parallelism (``LMEngine(tp=)``) scales ONE decode stream over a
device mesh; this module adds the other serving axis: N INDEPENDENT
engine replicas — each a full :class:`~veles_tpu.serving.LMEngine`,
optionally TP-sharded over its own disjoint device slice — behind a
:class:`Router` that places each admitted request on one replica.
Replicas share nothing (no cross-replica KV, no shared queue), so
aggregate decode throughput scales with replica count while the router
keeps the serving contract intact:

- PLACEMENT is driven by the replicas' live ``serving/metrics.py``
  signals, nothing engine-internal: queue depth + busy lanes scaled by
  the replica's decode-step EWMA (its measured pace, not its nominal
  one), the TTFT EWMA as the queueing penalty, and resident-KV-page
  pressure on paged pools.  Ties (an idle fleet) break by fewest
  requests routed, so cold traffic spreads evenly instead of piling
  on replica 0.  ``policy="round_robin"`` ignores the signals — the
  skew-measurement baseline ``tools/load_gen.py`` reads against.
- ADMISSION semantics are unchanged: the router tries replicas in
  placement order and re-raises the engines' own
  :class:`~veles_tpu.serving.batcher.Overloaded` /
  :class:`~veles_tpu.serving.batcher.PoolExhausted` only when EVERY
  live replica refused (HTTP 429 upstream, same as one engine) — with
  ``retry_after`` aggregated as the MINIMUM over the refusing replicas,
  since the client may retry as soon as ANY replica frees; deadline
  sheds (503) and client errors (ValueError → 400) pass through
  untouched.  A single replica degenerates to exactly today's
  one-engine path — same outputs, same errors.
- A SICK replica HOT-UNREGISTERS (:meth:`Router.unregister`): it
  leaves the placement rotation immediately and every request the
  router still has pending on it — queued or mid-decode — is
  withdrawn and REQUEUED on the surviving replicas.  A request is
  completed exactly once: a requeue only fires for work the drain
  itself interrupted (cancelled, or returned short), never for a
  result that arrived whole.  Requests never wedge: when no live
  replica can take a requeued request, its future fails loudly.

The RESILIENCE layer (ISSUE 10) adds three opt-in behaviors, all
default-off so an untouched router is bit-identical to the ISSUE 8
contract:

- RETRY (``retries=N``): an engine-level FAULT on a live replica
  (injected dispatch error, poisoned step — not Overloaded, not a
  deadline shed, not a client error) re-places the request WHOLE on a
  different replica after an exponential, seeded-jitter backoff,
  up to N times.  Re-placement is idempotent: replicas are
  bit-identical greedy decoders, the failed attempt delivered nothing,
  so the retried output is exactly what the first attempt would have
  produced — exactly-once at the client, metered as
  ``requests_retried``.
- HEDGING (``hedge_after_s=T``): a request still outstanding past the
  tail threshold (fixed ``T`` seconds, or ``T < 0`` for 1.5× the live
  latency p95) is DUPLICATED on a second replica; the first completed
  attempt wins and resolves the client future, the loser is cancelled
  through the engines' existing sibling-cancellation path.  Greedy
  parity makes both attempts bit-identical, so hedging can only move
  latency, never output.  Metered as ``requests_hedged`` /
  ``hedge_wins`` (wins = the hedge finished first).
- HEALTH (:class:`HealthChecker`): a background prober that
  auto-quarantines a wedged or failing replica through the existing
  ``unregister`` draining path and auto-reregisters it after a
  cooldown with half-open circuit-breaker semantics — see its
  docstring for the state machine (also documented in USAGE.md
  "Failure semantics").

ZERO-DOWNTIME WEIGHT UPDATES (ISSUE 11, :meth:`Router.deploy`) roll a
new checkpoint across the fleet canary-first: one replica leaves the
rotation (pending work drains onto the survivors through the exact
path above), hot-swaps via ``LMEngine.swap_weights`` (structural
mismatch → the deploy auto-rolls back before any client saw the new
weights), answers a PARITY PROBE whose expected continuation is
computed from the new weights themselves (a swap that serves anything
else is corrupt), then rejoins with a configurable traffic fraction
steered at it while the deploy WATCHES the same live signals the
:class:`HealthChecker` reads — decode-step/TTFT EWMAs vs the fleet,
the error counters, and the health circuit itself (a canary the
checker quarantines mid-watch rolls back).  Healthy canaries ramp to
the rest of the fleet in ``ramp``-sized groups; anything else swaps
the canary back to the previous version.  Evidence:
``weights_version{replica=}`` gauges, ``deploys_total`` /
``rollbacks_total`` counters, and every reply stamped with the
``weights_version`` that produced it (mixed fleets are attributable
mid-rollout).  ``serving/model_manager.py`` drives this loop from a
snapshot directory.

The router's own :class:`ServingMetrics` meters placement
(``routed_requests{replica="i"}`` labeled counters, ``requeued``,
rejected), the resilience layer (``requests_retried``,
``requests_hedged``, ``hedge_wins``, ``circuit_open_total``,
``replica_health_state{replica="i"}``), and each replica's engine
metrics register under one family name with a ``{replica="i"}`` label —
``/metrics`` renders one ``# TYPE`` line per family with one row per
replica, and ``/metrics.json`` (via :class:`RouterMetrics`) embeds
every replica's snapshot under ``"replicas"``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck, tracing
from veles_tpu.serving.batcher import Overloaded
from veles_tpu.serving.metrics import ServingMetrics


def replica_device_slices(replicas, tp, devices=None):
    """The device slice each replica owns: replica ``i`` gets devices
    ``[i*tp, (i+1)*tp)`` when tensor-parallel (validated against the
    host's device count up front), one device round-robin otherwise.
    THE one replica→devices mapping — ``serve_lm`` and
    ``tools/lm_bench.py`` both consume it, so the bench measures the
    placement the server actually ships."""
    import jax
    devices = list(devices if devices is not None else jax.devices())
    n_rep = max(1, int(replicas))
    tp_n = int(tp or 0)
    if tp_n >= 2:
        if n_rep * tp_n > len(devices):
            raise ValueError(
                "replicas=%d × tp=%d needs %d devices, have %d"
                % (n_rep, tp_n, n_rep * tp_n, len(devices)))
        return [devices[i * tp_n:(i + 1) * tp_n] for i in range(n_rep)]
    return [[devices[i % len(devices)]] for i in range(n_rep)]


class NoLiveReplicas(Overloaded):
    """Every replica is out of rotation (quarantined or drained) — a
    TRANSIENT unavailability, served upstream as the retryable 429 +
    ``Retry-After`` the failure-semantics contract promises, never a
    500 (the fleet usually returns at the next half-open probe)."""

    def __init__(self, retry_after=1.0):
        RuntimeError.__init__(
            self, "router has no live replicas (all quarantined or "
                  "drained); retry after %.1fs" % retry_after)
        self.retry_after = retry_after


class RouterMetrics(ServingMetrics):
    """Router-owned metrics whose ``snapshot()`` additionally embeds
    each replica engine's snapshot under ``"replicas"`` — one
    ``/metrics.json`` fetch covers the whole fleet."""

    def __init__(self, name="lm_router", labels=None):
        super().__init__(name, labels=labels)
        self._router = None

    def snapshot(self):
        snap = super().snapshot()
        router = self._router
        if router is not None:
            snap["replicas"] = [e.metrics.snapshot()
                                for e in router.replicas]
        return snap


class _Attempt:
    """One engine-side placement of a job.  A job normally has exactly
    one; hedging adds a second, and the first to settle wins."""

    __slots__ = ("job", "replica", "engine_future", "requeue",
                 "is_hedge", "abandoned", "span")

    def __init__(self, job, is_hedge=False):
        self.job = job
        self.replica = None
        self.engine_future = None
        #: tracing (ISSUE 12): this attempt's open span handle
        self.span = None
        #: set by unregister() right before it withdraws the engine-side
        #: request: tells the completion callback that a cancellation or
        #: short result is drain fallout to REPLACE, not a client event
        self.requeue = False
        self.is_hedge = is_hedge
        #: set when a drain timeout force-replaced this attempt while
        #: its engine was WEDGED: whatever the zombie engine eventually
        #: resolves is ignored (the replacement owns the client future)
        self.abandoned = False


class _Job:
    """One routed request: the client-facing future plus its live
    engine-side placements."""

    __slots__ = ("prompt", "n_new", "future", "t0", "replica", "live",
                 "requeues", "retries", "hedged", "last_exc", "version",
                 "delivered", "trace", "own_trace")

    def __init__(self, prompt, n_new):
        self.prompt = prompt
        self.n_new = int(n_new)
        self.future = Future()
        #: tracing (ISSUE 12): the request's TraceContext (or None),
        #: and whether the ROUTER rooted it (finished in _forget, once
        #: every attempt — hedge losers included — has settled)
        self.trace = None
        self.own_trace = False
        self.future.job = self          # router-level cancellation handle
        self.t0 = time.monotonic()
        #: replica of the newest placement (the WINNING attempt's after
        #: delivery) — what restful_api stamps into ``"replicas"``
        self.replica = None
        #: the weights_version that produced the delivered tokens
        #: (ISSUE 11) — what restful_api stamps into "weights_version"
        self.version = None
        #: delivery claim (router lock): exactly one attempt stamps
        #: replica/version and resolves the future
        self.delivered = False
        #: live attempts (guarded by the router lock)
        self.live = set()
        self.requeues = 0
        self.retries = 0
        self.hedged = False
        self.last_exc = None


class Router(Logger):
    """Place requests on ``replicas`` (started/stopped together) by
    their live metrics; see the module docstring for the contract.

    ``retries`` / ``hedge_after_s`` arm the ISSUE 10 resilience
    behaviors (default OFF — zero behavior change for existing
    callers); ``seed`` makes the retry jitter reproducible; ``faults``
    attaches a :class:`~veles_tpu.serving.faults.FaultPlan` whose
    ``router.place`` site fires per placement attempt."""

    POLICIES = ("metrics", "round_robin")

    #: lock-discipline map (ISSUE 15): placement state is touched by
    #: client threads, engine-worker completion callbacks, retry
    #: timers, the hedge loop and the health checker — everything
    #: shared lives under ``_lock``.  ``_deploy_lock`` serializes
    #: whole deploys and guards no attributes.  Job/attempt fields
    #: (job.live, job.delivered) are guarded by ``_lock`` too —
    #: documented on _Job, enforced by review (the pass is per-class
    #: attribute scoped).
    _guarded_by = {
        "_live": "_lock",
        "_routed": "_lock",
        "_pending": "_lock",
        "_jobs": "_lock",
        "_timers": "_lock",
        "_stopping": "_lock",
        "_rr": "_lock",
        "_canary": "_lock",
        "_canary_fraction": "_lock",
        "_rng": "_lock",
    }

    def __init__(self, replicas, metrics=None, name="lm_router",
                 policy="metrics", retries=0, retry_backoff_s=0.05,
                 retry_backoff_cap_s=2.0, hedge_after_s=0.0,
                 drain_timeout_s=5.0, seed=0, faults=None,
                 tracer=None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy not in self.POLICIES:
            raise ValueError("unknown router policy %r (one of %r)"
                             % (policy, self.POLICIES))
        self.name = name
        self.replicas = replicas
        self.policy = policy
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self.hedge_after_s = float(hedge_after_s or 0.0)
        self.drain_timeout_s = float(drain_timeout_s)
        self.metrics = metrics or ServingMetrics(name)
        if isinstance(self.metrics, RouterMetrics):
            self.metrics._router = self
        self._faults = faults
        #: optional serving/tracing.py SpanTracer (ISSUE 12) — one
        #: attribute-is-None check per site when unarmed
        self._tracer = tracer
        self._live = [True] * len(replicas)
        self._routed = [0] * len(replicas)
        self._pending = [set() for _ in replicas]
        self._jobs = set()              # outstanding (hedge scan set)
        self._timers = set()            # pending retry timers
        self._lock = lockcheck.make_lock("router._lock")
        self._rng = numpy.random.RandomState(seed)
        self._rr = 0
        self._stopping = False
        self._hedge_thread = None
        self._hedge_wake = threading.Event()
        #: canary traffic steering (ISSUE 11): while a deploy watches
        #: its canary, placement prefers the canary set with this
        #: probability and the rest of the fleet otherwise
        self._canary = frozenset()
        self._canary_fraction = 0.0
        self._deploy_lock = lockcheck.make_lock("router._deploy_lock")
        self.metrics.set_gauge("replicas_total", len(replicas))
        self.metrics.set_gauge("replicas_live", len(replicas))
        for i in range(len(replicas)):
            self._note_version(i)

    # ----------------------------------------------------------- properties
    @property
    def spec_k(self):
        """Speculation headroom upstream admission must reserve — the
        replicas share a config, but take the max so a heterogeneous
        fleet still reserves enough for any placement."""
        return max(e.spec_k for e in self.replicas)

    @property
    def max_len(self):
        return min(e.max_len for e in self.replicas)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        for e in self.replicas:
            e.start()
        if self.hedge_after_s:
            self._hedge_wake.clear()
            self._hedge_thread = threading.Thread(
                target=self._hedge_loop, daemon=True,
                name="router-hedge-%s" % self.name)
            self._hedge_thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stopping = True
            timers = list(self._timers)
            self._timers.clear()
            jobs = list(self._jobs)
        for t in timers:
            t.cancel()
        self._hedge_wake.set()
        if self._hedge_thread is not None:
            self._hedge_thread.join(timeout=10)
            self._hedge_thread = None
        # a job parked on a cancelled retry timer has no live attempt
        # left to resolve it — fail it loudly instead of wedging the
        # client on a future nobody owns
        for job in jobs:
            with self._lock:
                orphan = not job.live and not job.future.done()
            if orphan:
                self._settle_exc(job,
                                 job.last_exc
                                 or RuntimeError("router stopped"))
                self._forget(job)
        for e in self.replicas:
            e.stop()

    @staticmethod
    def _settle_exc(job, exc):
        """Fail the client future unless a concurrent path (a hedge
        sibling's delivery, stop()'s orphan sweep, a racing retry
        timer) already settled it — the Future's own state transition
        is the arbiter, exactly like _deliver's result race."""
        try:
            job.future.set_exception(exc)
        except Exception:   # noqa: BLE001 — someone else settled it
            pass

    # ------------------------------------------------------------ placement
    def _fault(self, site):
        if self._faults is not None:
            self._faults.fire(site)

    def _score(self, i):
        """Smaller = place here.  Everything read from the replica's
        live ServingMetrics: outstanding work (queue depth + busy
        lanes) scaled by the replica's measured decode-step EWMA (a
        slow replica's queue costs more wall than a fast one's), the
        TTFT EWMA weighted by queue depth (the queueing penalty new
        arrivals actually feel), and fractional resident-KV-page
        pressure as the paged-pool tiebreak."""
        m = self.replicas[i].metrics
        depth = m.gauge("queue_depth", 0) + m.gauge("slots_busy", 0)
        step = m.ewma("decode_step", 0.0) or 1e-4
        score = depth * step + m.ewma("ttft", 0.0) * m.gauge(
            "queue_depth", 0)
        kv_total = m.gauge("kv_pages_total", 0)
        if kv_total:
            score += (1.0 - m.gauge("kv_pages_free", kv_total)
                      / kv_total) * step
        return score

    def _note_version(self, i):
        """Export replica i's serving checkpoint generation as the
        ``weights_version{replica=}`` gauge (ISSUE 11)."""
        v = getattr(self.replicas[i], "weights_version", None)
        if isinstance(v, (int, float)):
            self.metrics.set_gauge("weights_version", v,
                                   labels={"replica": str(i)})

    def _order(self):
        """Live replica indices, best placement first.  While a deploy
        watches a canary, a seeded coin steers ``_canary_fraction`` of
        placements to the canary set first (the rest of the fleet
        remains the admission fallback either way)."""
        with self._lock:
            live = [i for i, ok in enumerate(self._live) if ok]
            if self.policy == "round_robin":
                self._rr += 1
                start = self._rr
            routed = list(self._routed)
            canary = self._canary
            pick_canary = bool(canary) and \
                self._rng.random_sample() < self._canary_fraction
        if not live:
            raise NoLiveReplicas()
        if self.policy == "round_robin":
            order = [live[(start + j) % len(live)]
                     for j in range(len(live))]
        else:
            order = sorted(live,
                           key=lambda i: (self._score(i), routed[i], i))
        if canary:
            order = ([i for i in order if (i in canary) == pick_canary]
                     + [i for i in order
                        if (i in canary) != pick_canary])
        return order

    def submit(self, prompt, n_new):
        """Queue one prompt on the best replica; returns a Future for
        the (n_new,) greedy continuation.  Raises exactly what one
        engine would: ValueError for client errors, Overloaded /
        PoolExhausted when every live replica refuses admission (with
        ``retry_after`` = the MINIMUM over the refusing replicas)."""
        job = _Job(prompt, int(n_new))
        # tracing (ISSUE 12): join the caller's context (HTTP) or root
        # one here (direct router use) — the attempt spans _place opens
        # nest under it, so retries/hedges/drains read as one timeline.
        # A router-rooted trace finishes in _forget, NOT at future
        # resolution: a hedge loser's attempt may still be settling
        # when the winner unblocks the client, and its span must close
        # before the tree is sealed.  A sampled-out decision (ours or
        # upstream's) leaves job.trace None; _place propagates it so
        # the engines never re-roll the coin.
        if self._tracer is not None:
            ctx, job.own_trace = tracing.join_or_root(
                self._tracer, "request", "router")
            job.trace = None if ctx is tracing.SAMPLED_OUT else ctx
        with self._lock:
            self._jobs.add(job)
        try:
            self._place(job)
        except Exception as e:
            with self._lock:
                self._jobs.discard(job)
            if job.own_trace:
                job.trace.tracer.finish_request(job.trace, error=e)
            raise
        return job.future

    def _place(self, job, exclude=(), hedge=False):   # hot-path
        """Place one attempt for ``job``.  ``exclude`` replicas are
        tried last (retry-on-a-different-replica) — or not at all when
        ``hedge`` (a duplicate on the same replica hedges nothing).
        Returns True when placed; a failed hedge returns False
        (best-effort), a failed primary placement raises."""
        last_exc = None
        min_retry = None
        order = self._order()
        if exclude:
            preferred = [i for i in order if i not in exclude]
            order = preferred if hedge \
                else preferred + [i for i in order if i in exclude]
        for i in order:
            engine = self.replicas[i]
            with self._lock:
                if not self._live[i]:
                    continue
            att = _Attempt(job, is_hedge=hedge)
            trc = job.trace
            if trc is not None:
                att.span = trc.tracer.begin(
                    trc, "attempt", cat="router",
                    attrs={"replica": i, "hedge": hedge,
                           "retry": job.retries,
                           "requeue": job.requeues})
            try:
                self._fault("router.place")
                if att.span is not None:
                    # the engine's spans nest under THIS attempt
                    with tracing.use(trc.at(att.span[1])):
                        f = engine.submit(job.prompt, job.n_new)
                elif self._tracer is not None:
                    # sampled out (or a late zombie re-place of a
                    # sealed trace): tell the engine the decision is
                    # made — it must not root a stray partial trace
                    with tracing.use(tracing.SAMPLED_OUT):
                        f = engine.submit(job.prompt, job.n_new)
                else:
                    f = engine.submit(job.prompt, job.n_new)
            except Overloaded as exc:
                # queue/pool pressure on this replica: the next-best
                # may still have room (ValueError — a client error —
                # propagates immediately: it is identical on every
                # replica of a homogeneous fleet).  Track the SMALLEST
                # Retry-After seen: the client may come back as soon
                # as the soonest-freeing replica frees, not the
                # last-tried one (ISSUE 10 satellite).
                last_exc = exc
                if att.span is not None:
                    trc.tracer.end(att.span, error=exc)
                ra = getattr(exc, "retry_after", None)
                if ra is not None:
                    min_retry = ra if min_retry is None \
                        else min(min_retry, ra)
                continue
            except Exception as exc:
                # a client error (ValueError) propagates to the caller
                # — close the attempt span on the way out
                if att.span is not None:
                    trc.tracer.end(att.span, error=exc)
                raise
            att.replica = i
            att.engine_future = f
            with self._lock:
                # re-check at COMMIT: a drain that ran between the
                # pre-submit check and here already snapshotted
                # _pending[i] without this attempt (stranding it on the
                # drained replica), and a sibling attempt may have
                # DELIVERED in the same window (a committed duplicate
                # would decode to completion for nobody) — withdraw in
                # either case
                done = job.future.done()
                stale = done or not self._live[i]
                if not stale:
                    self._pending[i].add(att)
                    job.live.add(att)
                    self._routed[i] += 1
                    job.replica = i
            if stale:
                engine._cancel(f.request)
                if att.span is not None:
                    trc.tracer.end(att.span, attrs={"stale": True})
                if done:
                    return True      # settled — nothing left to place
                continue
            if hedge:
                self.metrics.inc("requests_hedged")
            else:
                self.metrics.record_enqueue()
            self.metrics.inc("routed_requests",
                             labels={"replica": str(i)})
            f.add_done_callback(
                lambda f, att=att: self._on_attempt_done(att, f))
            return True
        if hedge:
            return False
        self.metrics.record_reject()
        if last_exc is not None:
            if min_retry is not None:
                last_exc.retry_after = min_retry
            raise last_exc
        raise Overloaded()

    # ----------------------------------------------------------- completion
    def _on_attempt_done(self, att, engine_future):
        """Runs on the replica's worker (or canceller) thread when an
        engine-side future settles.  Exactly-once delivery: the
        router future is resolved here and only here — the first
        settled attempt wins, siblings are cancelled and ignored —
        and a requeue fires only for drain fallout (_Attempt.requeue)."""
        job = att.job
        i = att.replica
        if att.span is not None:
            if engine_future.cancelled():
                outcome = "cancelled"
            elif engine_future.exception() is not None:
                outcome = "error"
            else:
                outcome = "ok"
            job.trace.tracer.end(
                att.span, attrs={"outcome": outcome},
                error=(engine_future.exception()
                       if outcome == "error" else None))
        with self._lock:
            # membership in job.live is the CLAIM: a drain timeout that
            # force-replaced this attempt already removed it (and owns
            # the job now) — this late resolution belongs to a zombie
            claimed = att in job.live
            self._pending[i].discard(att)
            job.live.discard(att)
            others = bool(job.live)
            live = self._live[i]
            stopping = self._stopping
        if att.abandoned or not claimed:
            self._forget(job)
            return
        if job.future.done():            # withdrawn, or a sibling won
            self._forget(job)
            return
        # a live SIBLING attempt already guarantees delivery: drain
        # fallout on this one never needs a replacement decode (the
        # `others` guards below) — re-placing anyway would duplicate
        # the work on the shrunken fleet exactly when it is drained
        requeue = att.requeue and not stopping
        if engine_future.cancelled():
            if requeue and not others:
                # withdrawn before any decode: drain fallout replaces
                # it; a router-level cancellation stays cancelled
                self._replace(job)
            elif others:
                pass                     # a cancelled hedge loser
            else:
                job.future.cancel()
                self._forget(job)
            return
        exc = engine_future.exception()
        if exc is not None:
            from veles_tpu.serving.batcher import DeadlineExceeded
            benign = isinstance(exc, (Overloaded, DeadlineExceeded))
            if (requeue or not live) and not others and not stopping \
                    and not benign:
                # in-flight work dying WITH its drained/sick replica
                # (engine stopped, poisoned step) is the router's
                # problem, whatever the retry budget says
                self._replace(job)
                return
            if others:
                # a hedge sibling is still decoding — let it deliver
                job.last_exc = exc
                return
            if not benign and not stopping and self.retries \
                    and job.retries < self.retries:
                # engine-level FAULT on a live replica: re-place WHOLE
                # on a different replica after a jittered backoff —
                # idempotent, because greedy replicas are bit-identical
                # and the failed attempt delivered nothing
                self._schedule_retry(job, exc, exclude={i})
                return
            self._settle_exc(job, exc)
            self._forget(job)
            return
        result = engine_future.result()
        if requeue and len(result) < job.n_new:
            # the drain interrupted this lane mid-decode: the engine
            # resolved it with the tokens it had (its cancellation
            # path) — rerun the request whole on a live replica,
            # unless a sibling attempt is already decoding it
            if not others:
                self._replace(job)
            return
        self._deliver(job, att, result)

    def _deliver(self, job, att, result):
        """First settled attempt wins; the set_result race (two
        attempts completing concurrently) is decided under the router
        lock: exactly ONE attempt claims delivery and stamps
        replica/version — a losing hedge sibling must never overwrite
        the winner's stamps (during a canary deploy the two replicas
        can serve different weights_version)."""
        with self._lock:
            if job.delivered or job.future.done():
                return
            job.delivered = True
            # stamped BEFORE the result resolves so a waiter unblocked
            # by set_result reads the WINNING attempt's stamps
            job.replica = att.replica
            job.version = getattr(att.engine_future, "version", None)
            if job.trace is not None:
                # close the losing siblings' open spans BEFORE the
                # client unblocks: an HTTP-owned root seals the trace
                # the moment the handler returns, and a still-open
                # hedge-loser attempt would be flagged unclosed —
                # breaking the asserted span-tree integrity.  (The
                # losers' engine-side work is cancelled below, after
                # set_result, exactly as before.)
                for loser in job.live:
                    job.trace.tracer.end(
                        loser.span, attrs={"outcome": "hedge-lost"})
                    lreq = getattr(loser.engine_future, "request",
                                   None)
                    if lreq is not None and lreq.tspan is not None:
                        job.trace.tracer.end(lreq.tspan,
                                             error="hedge-lost")
        try:
            job.future.set_result(result)
        except Exception:   # noqa: BLE001 — cancelled/settled meanwhile
            return
        if att.is_hedge:
            self.metrics.inc("hedge_wins")
        self.metrics.record_response(time.monotonic() - job.t0)
        with self._lock:
            losers = list(job.live)
        for loser in losers:
            # the loser's callback sees the done future and exits
            self.replicas[loser.replica]._cancel(
                loser.engine_future.request)
        self._forget(job)

    def _forget(self, job):
        with self._lock:
            settled = not job.live
            if settled:
                self._jobs.discard(job)
        if settled and job.own_trace and job.future.done():
            # every attempt settled AND the client future resolved:
            # the span tree is complete — seal it (idempotent)
            tracing.finish_from_future(job.trace, job.future)

    def _replace(self, job):
        """Re-place a drain-interrupted job on the surviving replicas —
        or fail it loudly when none can take it (never wedge)."""
        if job.future.done():
            # raced a router-level cancellation (generate() sibling
            # withdrawal): nobody reads this result — do not spend a
            # healthy replica's slots rerunning it
            self._forget(job)
            return
        job.requeues += 1
        self.metrics.inc("requeued_requests")
        if job.trace is not None:
            job.trace.tracer.instant(
                job.trace, "drain.requeue", cat="router",
                attrs={"requeue": job.requeues})
        if job.requeues > len(self.replicas) + 1:
            self._settle_exc(job, RuntimeError(
                "request could not be re-placed after %d drain retries"
                % job.requeues))
            self._forget(job)
            return
        try:
            self._place(job)
        except Exception as exc:   # noqa: BLE001 — delivered, not raised
            self._settle_exc(job, exc)
            self._forget(job)

    # -------------------------------------------------------------- retry
    def _schedule_retry(self, job, exc, exclude):
        job.retries += 1
        job.last_exc = exc
        self.metrics.inc("requests_retried")
        delay = min(self.retry_backoff_cap_s,
                    self.retry_backoff_s * (2 ** (job.retries - 1)))
        if job.trace is not None:
            job.trace.tracer.instant(
                job.trace, "retry.backoff", cat="router",
                attrs={"retry": job.retries,
                       "base_delay_s": round(delay, 4)})
        with self._lock:
            # seeded jitter (deterministic for a fixed retry order):
            # desynchronizes a burst of same-fault retries so they do
            # not land on the survivor as one thundering herd
            delay += float(self._rng.uniform(0.0, delay * 0.5))
            if self._stopping:
                stopping = True
            else:
                stopping = False
                timer = threading.Timer(
                    delay, self._retry_place, args=(job, exclude))
                timer.daemon = True
                self._timers.add(timer)
        if stopping:
            self._settle_exc(job, exc)
            self._forget(job)
            return
        timer.start()

    def _retry_place(self, job, exclude):
        with self._lock:
            # drop timers whose threads finished (this one is still
            # alive while its callback runs; it prunes next round)
            self._timers = {t for t in self._timers if t.is_alive()}
            stopping = self._stopping
        if job.future.done():
            self._forget(job)
            return
        if stopping:
            self._settle_exc(job,
                             job.last_exc
                             or RuntimeError("router stopped"))
            self._forget(job)
            return
        try:
            self._place(job, exclude=exclude)
        except Exception as exc:   # noqa: BLE001 — delivered, not raised
            self._settle_exc(job, exc)
            self._forget(job)

    # ------------------------------------------------------------- hedging
    def _hedge_threshold(self):
        """Seconds outstanding before a request hedges: the fixed
        ``hedge_after_s``, or (when negative) 1.5× the live latency
        p95 — None until enough responses exist to estimate a tail."""
        if self.hedge_after_s > 0:
            return self.hedge_after_s
        p95 = self.metrics.latency_quantile(0.95)
        if p95 is None:
            return None
        return max(0.02, 1.5 * p95)

    def _hedge_loop(self):
        interval = max(0.005, self.hedge_after_s / 4) \
            if self.hedge_after_s > 0 else 0.02
        while not self._hedge_wake.wait(interval):
            thr = self._hedge_threshold()
            if thr is None:
                continue
            now = time.monotonic()
            with self._lock:
                jobs = [j for j in self._jobs
                        if not j.hedged and len(j.live) == 1]
                live_n = sum(1 for ok in self._live if ok)
            if live_n < 2:
                continue
            for job in jobs:
                if job.future.done() or now - job.t0 < thr:
                    continue
                with self._lock:
                    exclude = {a.replica for a in job.live}
                    job.hedged = True
                try:
                    # best-effort: a refused hedge (fleet under
                    # pressure) just leaves the primary to finish
                    self._place(job, exclude=exclude, hedge=True)
                except Exception:   # noqa: BLE001 — hedge is optional
                    pass

    # --------------------------------------------------------------- client
    def generate(self, prompts, n_new, return_replicas=False,
                 return_versions=False):
        """Decode a (b, s) prompt batch across the fleet; returns
        (b, s + n_new) int32 (with ``return_replicas`` also the
        replica index that served each row, with ``return_versions``
        the ``weights_version`` each row decoded under — mixed during
        a rolling deploy).  All-or-nothing sibling cancellation,
        exactly like ``LMEngine.generate``."""
        prompts = numpy.asarray(prompts, numpy.int32)
        futures = []
        try:
            for row in prompts:
                futures.append(self.submit(row, n_new))
            news = numpy.stack([f.result() for f in futures])
        except Exception:
            for f in futures:
                self.cancel(f)
            raise
        out = numpy.concatenate([prompts, news], axis=1)
        extras = []
        if return_replicas:
            extras.append([f.job.replica for f in futures])
        if return_versions:
            extras.append([f.job.version for f in futures])
        if extras:
            return (out, *extras)
        return out

    def cancel(self, future):
        """Withdraw a routed request (sibling cancellation): every
        engine-side attempt is cancelled and the router future will
        NOT be re-placed."""
        job = future.job
        with self._lock:
            attempts = list(job.live)
        for att in attempts:
            att.requeue = False
            if att.engine_future is not None:
                self.replicas[att.replica]._cancel(
                    att.engine_future.request)
        future.cancel()
        self._forget(job)

    # ---------------------------------------------------------------- drain
    def unregister(self, i, reason="sick"):
        """Hot-unregister replica ``i``: it leaves the placement
        rotation NOW, and every request the router still has pending
        on it is withdrawn and re-placed on the surviving replicas
        (queued requests requeue unserved; a mid-decode lane is
        cancelled and its request reruns whole elsewhere — no loss,
        no duplicate completion).  The engine itself keeps running —
        the caller decides whether to stop or restart it; re-admit
        with :meth:`reregister`.  Returns the number of placements
        withdrawn."""
        with self._lock:
            if not self._live[i]:
                return 0
            self._live[i] = False
            attempts = list(self._pending[i])
            live_now = sum(1 for ok in self._live if ok)
        self.metrics.set_gauge("replicas_live", live_now)
        self.metrics.inc("replica_drains")
        if self._tracer is not None:
            self._tracer.event(
                "router.drain", cat="router",
                attrs={"replica": i, "reason": str(reason),
                       "withdrawn": len(attempts)})
        self.warning("draining replica %d (%s): re-placing %d pending "
                     "request(s) on %d live replica(s)",
                     i, reason, len(attempts), live_now)
        engine = self.replicas[i]
        for att in attempts:
            att.requeue = True
            engine._cancel(att.engine_future.request)
            if not att.engine_future.done():
                # a WEDGED engine (frozen worker, hung device call)
                # cannot resolve its side of a mid-decode withdrawal —
                # after drain_timeout_s the attempt is force-abandoned
                # and the request re-placed anyway, so a drain never
                # wedges a client behind a dead worker.  If the zombie
                # later thaws, its resolution is ignored (the claim
                # check in _on_attempt_done) — exactly-once holds.
                timer = threading.Timer(self.drain_timeout_s,
                                        self._force_replace, args=(att,))
                timer.daemon = True
                with self._lock:
                    if not self._stopping:
                        self._timers.add(timer)
                        timer.start()
        return len(attempts)

    def _force_replace(self, att):
        """Drain-timeout fallout: abandon a wedged attempt and re-place
        its job (see unregister)."""
        job = att.job
        with self._lock:
            self._timers = {t for t in self._timers if t.is_alive()}
            if self._stopping or att not in job.live:
                return           # settled (or settling) normally
            att.abandoned = True
            job.live.discard(att)
            self._pending[att.replica].discard(att)
        if job.future.done():
            self._forget(job)
            return
        self.metrics.inc("drain_forced_replacements")
        if att.span is not None:
            job.trace.tracer.end(att.span, error="drain-abandoned")
        self.warning("replica %d never resolved a drained request in "
                     "%.1fs: force re-placing it", att.replica,
                     self.drain_timeout_s)
        self._replace(job)

    def reregister(self, i):
        """Return a drained replica to the placement rotation (after a
        restart or recovery)."""
        with self._lock:
            self._live[i] = True
            live_now = sum(1 for ok in self._live if ok)
        self.metrics.set_gauge("replicas_live", live_now)

    # -------------------------------------------------------------- deploy
    def deploy(self, params, version=None, canary=1,
               canary_fraction=0.25, ramp=0, watch_s=0.0,
               watch_slow_ratio=5.0, probe=None, probe_prompt=(1, 2, 3),
               probe_n_new=4, probe_timeout_s=60.0, drain=False,
               checker=None, auto_rollback=True, swap_timeout_s=120.0):
        """Roll ``params`` (a portable LM param tree matching the
        fleet's structure) across the fleet canary-first; see the
        module docstring for the state flow.  Returns a record dict —
        ``{"version", "swapped", "rolled_back", "reason", ...}`` —
        and never raises for a bad canary when ``auto_rollback`` (the
        rollback IS the result); a structurally impossible tree
        surfaces as a rolled-back record too, since the old weights
        never stopped serving.

        ``canary``: replicas swapped (and probed) before any traffic
        ramp; >= the live fleet size means a plain rolling update.
        ``canary_fraction``: share of placements steered at the canary
        during the ``watch_s`` observation window.  ``ramp``: fleet
        replicas swapped per round after the canary passes (0 = rest
        at once).  ``probe``: ``(prompt, expected_tokens)`` known-good
        pair — default None computes the expected continuation from
        ``params`` itself via ``ops.transformer.generate`` (off the
        hot path; catches a swap that serves anything but the new
        weights); ``False`` disables the probe.  ``checker``: a
        :class:`HealthChecker` whose circuit state the watch phase
        also consults — a canary it quarantines (via its synchronous
        ``step()`` or its thread) rolls the deploy back.  ``drain``
        is forwarded to ``swap_weights`` (True replaces in-flight
        lanes on the new weights instead of finishing them on the
        old)."""
        if not self._deploy_lock.acquire(blocking=False):
            raise RuntimeError("another deploy is already in flight")
        try:
            return self._deploy(params, version, canary,
                                canary_fraction, ramp, watch_s,
                                watch_slow_ratio, probe, probe_prompt,
                                probe_n_new, probe_timeout_s, drain,
                                checker, auto_rollback, swap_timeout_s)
        finally:
            self._deploy_lock.release()

    def _deploy(self, params, version, canary, canary_fraction, ramp,
                watch_s, watch_slow_ratio, probe, probe_prompt,
                probe_n_new, probe_timeout_s, drain, checker,
                auto_rollback, swap_timeout_s):
        with self._lock:
            live = [i for i, ok in enumerate(self._live) if ok]
        if not live:
            raise NoLiveReplicas()
        if version is None:
            version = 1 + max(
                int(getattr(e, "weights_version", 0) or 0)
                for e in self.replicas)
        version = int(version)
        self.metrics.inc("deploys_total")
        record = {"version": version, "canary": [], "swapped": [],
                  "rolled_back": False, "reason": None,
                  "probe_ok": None, "completed": False}
        prev = {}       # i -> (old params, old version) for rollback
        pulled = set()  # replicas deploy unregistered and still holds
        expected = self._probe_expected(params, probe, probe_prompt,
                                        probe_n_new)
        canaries = live[:max(0, min(int(canary), len(live)))]
        rest = [i for i in live if i not in canaries]
        record["canary"] = list(canaries)

        def fail(why, bad=None):
            """``bad`` names a replica PROVEN to serve wrong output
            (failed parity probe): without auto-rollback it must stay
            out of rotation — clients never reach it."""
            if auto_rollback:
                self._rollback(prev, pulled, record, why, drain,
                               swap_timeout_s)
            else:
                record["reason"] = why
                record["needs_attention"] = True
                if bad is not None:
                    record["quarantined"] = [bad]
                for i in sorted(pulled):
                    if i != bad:
                        self.reregister(i)
                pulled.clear()
            return record

        for i in canaries:
            ok, why, bad = self._swap_replica(
                i, params, version, expected, drain, prev, pulled,
                record, probe_timeout_s, swap_timeout_s)
            if not ok:
                return fail(why, bad=i if bad else None)
        if canaries and rest:
            with self._lock:
                self._canary = frozenset(canaries)
                self._canary_fraction = float(canary_fraction)
            try:
                healthy, why = self._watch_canary(
                    canaries, watch_s, watch_slow_ratio, checker)
            finally:
                with self._lock:
                    self._canary = frozenset()
                    self._canary_fraction = 0.0
            if not healthy:
                return fail(why)
        group = max(1, int(ramp)) if ramp else max(1, len(rest))
        for g0 in range(0, len(rest), group):
            for i in rest[g0:g0 + group]:
                ok, why, bad = self._swap_replica(
                    i, params, version, expected, drain, prev, pulled,
                    record, probe_timeout_s, swap_timeout_s)
                if not ok:
                    return fail(why, bad=i if bad else None)
        record["completed"] = True
        if self._tracer is not None:
            self._tracer.event(
                "router.deploy", cat="deploy",
                attrs={"version": version,
                       "swapped": len(record["swapped"]),
                       "canary": len(canaries)})
        self.info("deploy v%d complete: %d replica(s) swapped "
                  "(canary %s)", version, len(record["swapped"]),
                  canaries)
        return record

    def _probe_expected(self, params, probe, probe_prompt, probe_n_new):
        """The parity probe's (prompt, known-good continuation): the
        caller's pair, or computed from the NEW params with the
        fleet's own decode config via the reference ``generate`` —
        off the hot path, so a correctly-swapped canary must
        reproduce it bit-exactly."""
        if probe is False or not probe_n_new:
            return None
        if probe is not None:
            prompt, want = probe
            return list(prompt), numpy.asarray(want, numpy.int32)
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import generate
        e0 = self.replicas[0]
        prompt = list(probe_prompt)
        row = numpy.asarray(generate(
            params, jnp.asarray([prompt], jnp.int32),
            int(probe_n_new), e0.n_heads, temperature=0.0,
            max_len=e0.max_len, rope=e0.rope, window=e0.window,
            sinks=e0.sinks))[0]
        return prompt, numpy.asarray(row[len(prompt):], numpy.int32)

    def _swap_replica(self, i, params, version, expected, drain, prev,
                      pulled, record, probe_timeout_s, swap_timeout_s):
        """Swap ONE replica out of rotation: unregister (pending work
        drains onto the survivors — the exactly-once path), hot-swap,
        parity-probe straight at the engine (no client traffic can
        reach bad weights), then rejoin.  A solo fleet skips the
        unregister — swap_weights alone keeps its lanes whole, at the
        cost of a brief no-isolation window the docstring owns up to.
        Returns ``(ok, why, bad)`` — ``bad`` True only when the
        replica was PROVEN to serve wrong output (failed probe), the
        one case it must never rejoin unrestored."""
        engine = self.replicas[i]
        prev.setdefault(i, (engine.params,
                            getattr(engine, "weights_version", 0)))
        with self._lock:
            solo = sum(1 for ok in self._live if ok) <= 1
            was_live = self._live[i]
        if was_live and not solo:
            self.unregister(i, reason="deploy v%d" % version)
            pulled.add(i)
        try:
            engine.swap_weights(params, version=version, drain=drain,
                                timeout_s=swap_timeout_s)
        except Exception as e:   # noqa: BLE001 — old weights serving
            return False, ("swap refused on replica %d: %s"
                           % (i, e)), False
        self._note_version(i)
        record["swapped"].append(i)
        if expected is not None:
            ok = self._parity_probe(engine, expected, probe_timeout_s)
            record["probe_ok"] = ok
            if not ok:
                # the replica serves WRONG output for the new weights:
                # leave it out of rotation until the rollback restores
                # the old ones
                return False, ("parity probe failed on replica %d "
                               "(v%d output != known-good)"
                               % (i, version)), True
        if i in pulled:
            self.reregister(i)
            pulled.discard(i)
        return True, None, False

    def _parity_probe(self, engine, expected, timeout_s):
        prompt, want = expected
        try:
            out = engine.submit(prompt, len(want)).result(
                timeout=timeout_s)
        except Exception as e:   # noqa: BLE001 — any failure = not ok
            self.warning("deploy parity probe errored: %s", e)
            return False
        return numpy.array_equal(numpy.asarray(out, numpy.int32), want)

    def _watch_canary(self, canaries, watch_s, slow_ratio, checker):
        """Observe the canary set for ``watch_s`` against the SAME
        live signals the health layer reads: quarantine (ours or the
        checker's circuit), new engine errors, and decode-step/TTFT
        EWMAs beyond ``slow_ratio``× the rest of the fleet."""
        base_err = {i: self.replicas[i].metrics.errors
                    for i in canaries}
        deadline = time.monotonic() + max(0.0, float(watch_s))
        while True:
            with self._lock:
                others = [j for j, ok in enumerate(self._live)
                          if ok and j not in canaries]
            for i in canaries:
                with self._lock:
                    live = self._live[i]
                if not live:
                    return False, ("canary %d was quarantined during "
                                   "the watch window" % i)
                if checker is not None \
                        and checker.states()[i] != checker.HEALTHY:
                    return False, ("canary %d health circuit is not "
                                   "closed" % i)
                m = self.replicas[i].metrics
                if m.errors > base_err[i]:
                    return False, ("canary %d errored during the "
                                   "watch window (%d new error(s))"
                                   % (i, m.errors - base_err[i]))
                for sig in ("decode_step", "ttft"):
                    mine = m.ewma(sig, 0.0)
                    ref = sorted(self.replicas[j].metrics.ewma(sig,
                                                               0.0)
                                 for j in others)
                    ref = [r for r in ref if r > 0.0]
                    if mine and ref \
                            and mine > slow_ratio * ref[len(ref) // 2]:
                        return False, (
                            "canary %d %s EWMA %.4fs exceeds %.1fx "
                            "the fleet median %.4fs"
                            % (i, sig, mine, slow_ratio,
                               ref[len(ref) // 2]))
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return True, None
            time.sleep(min(0.05, remaining))

    def _rollback(self, prev, pulled, record, why, drain,
                  swap_timeout_s):
        """Swap every replica that ACTUALLY swapped (``record
        ["swapped"]`` — the authoritative list; version-number equality
        is not, since a deploy may legitimately reuse the current
        number) back to its retained previous params.  A replica whose
        rollback swap itself fails stays OUT of rotation — bad weights
        must never rejoin."""
        record["rolled_back"] = True
        record["reason"] = why
        self.metrics.inc("rollbacks_total")
        self.warning("deploy v%s rolling back: %s", record["version"],
                     why)
        restored = set()
        for i in record["swapped"]:
            old_params, old_version = prev[i]
            try:
                self.replicas[i].swap_weights(
                    old_params, version=old_version, drain=drain,
                    timeout_s=swap_timeout_s)
            except Exception as e:   # noqa: BLE001 — stays quarantined
                self.warning(
                    "rollback of replica %d to v%s FAILED (%s): "
                    "leaving it out of rotation", i, old_version, e)
                continue
            self._note_version(i)
            restored.add(i)
        for i in sorted(pulled):
            # a refused swap never installed anything (safe to rejoin);
            # a swapped replica rejoins only once its restore succeeded
            if i not in record["swapped"] or i in restored:
                self.reregister(i)
        pulled.clear()

    # ------------------------------------------------------------- evidence
    def routed_counts(self):
        """Requests placed per replica (including requeues, retries and
        hedges) — the server-side balance evidence the bench records."""
        with self._lock:
            return list(self._routed)


class HealthChecker(Logger):
    """Background health prober with half-open circuit-breaker
    semantics per replica (ISSUE 10).

    STATE MACHINE (gauge ``replica_health_state{replica="i"}``):

    - HEALTHY (0): every :meth:`step`, the replica is checked two ways.
      STALENESS — if it holds work (queue depth + busy lanes > 0) but
      its progress counters (tokens emitted, prefill dispatches, i.e.
      the facts behind the decode-step EWMA) have not advanced for
      ``stall_s``, the decode loop is wedged: one failure.  PROBE — an
      IDLE replica gets a synthetic 1-token decode
      (``probe_timeout``-bounded, withdrawn on timeout so a wedged
      queue cannot accumulate probes): a failed or timed-out probe is
      one failure.  Any success resets the count;
      ``fail_threshold`` consecutive failures OPEN the circuit.
    - OPEN (1): the replica was auto-quarantined through
      :meth:`Router.unregister` — out of rotation, pending work
      drained onto the survivors (``circuit_open_total`` incremented).
      After ``cooldown_s`` (doubling per consecutive re-open, capped
      at ``cooldown_cap_s``) the circuit goes half-open.
    - HALF-OPEN (2): ONE synthetic probe, straight to the engine
      (it is out of rotation, so no client traffic is at risk).
      Success → :meth:`Router.reregister`, state HEALTHY, cooldown
      reset.  Failure → back to OPEN with the doubled cooldown.

    A replica an OPERATOR unregistered (router not-live while this
    checker still holds state HEALTHY) is left alone — the checker
    never fights a manual drain.

    SIZING ``stall_s``: the progress counters also stand still while
    the engine compiles a new program (a lazily-compiled prompt
    bucket on the non-chunked path can take seconds on CPU), which is
    indistinguishable from a wedge from out here — set ``stall_s``
    above the worst first-compile, or serve with ``prefill_chunk``
    (every program warmed at start) as production does.  The PROBE's
    own bucket is immune either way: :meth:`start` runs
    :meth:`warm_probes` first, so the synthetic probe's first compile
    happens before the monitoring clock starts and can never count as
    a probe timeout (drive :meth:`step` by hand without
    :meth:`start`? call ``warm_probes()`` yourself first).

    ``step()`` is public and synchronous: tests and the chaos harness
    drive the state machine deterministically without the thread;
    ``start()`` runs it every ``interval_s`` in the background.

    THREADING (ISSUE 15): the prober thread is not alone — the SLO
    monitor's ``note_slo_page`` / ``note_slo_ok`` hooks arrive on the
    telemetry sampler thread, and ``states()`` is read by deploy
    watches on theirs.  The circuit state (``_state``, ``_fails``,
    ``_slo_fails``, ``_cooldown``, ``_reopen_at``) therefore lives
    under ``_lock``; probes and quarantine side effects (router
    drains) run OUTSIDE it, so the lock is never held across an
    engine submit or the router's own lock any longer than a state
    read.  The progress clocks (``_last_progress``, ``_last_counts``,
    ``_warmed``) stay unguarded: they are touched only by the prober
    thread (or the test driving ``step()`` by hand in its place)."""

    HEALTHY, OPEN, HALF_OPEN = 0, 1, 2

    #: lock-discipline map (ISSUE 15, tools/veles_lint.py)
    _guarded_by = {
        "_state": "_lock",
        "_fails": "_lock",
        "_slo_fails": "_lock",
        "_cooldown": "_lock",
        "_reopen_at": "_lock",
    }

    def __init__(self, router, interval_s=1.0, probe_timeout_s=5.0,
                 fail_threshold=3, cooldown_s=5.0, cooldown_cap_s=60.0,
                 stall_s=None, probe_token=1, name="lm_health"):
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self.name = name
        self.router = router
        self.metrics = router.metrics
        self.interval_s = float(interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_cap_s = float(cooldown_cap_s)
        self.stall_s = float(stall_s) if stall_s is not None \
            else 3.0 * self.interval_s
        self.probe_token = int(probe_token)
        n = len(router.replicas)
        now = time.monotonic()
        self._lock = lockcheck.make_lock("health._lock")
        self._state = [self.HEALTHY] * n
        self._fails = [0] * n
        self._cooldown = [self.cooldown_s] * n
        self._reopen_at = [0.0] * n
        self._last_progress = [now] * n
        self._last_counts = [None] * n
        #: consecutive SLO page signals per replica (ISSUE 14) — kept
        #: SEPARATE from the probe loop's _fails: a slow-but-responsive
        #: replica keeps answering synthetic probes (which reset
        #: _fails), so page signals must accumulate on their own
        #: counter; the SLO monitor clears it via note_slo_ok when the
        #: burn stops
        self._slo_fails = [0] * n
        self._warmed = False
        self._stop = threading.Event()
        self._thread = None
        for i in range(n):
            self._set_state(i, self.HEALTHY)

    # ------------------------------------------------------------ lifecycle
    def warm_probes(self, timeout_s=60.0):
        """Run one synthetic probe against every replica BEFORE
        monitoring begins, so the probe prompt's first compile
        (seconds on CPU for a never-seen bucket) happens here instead
        of inside a ``probe_timeout_s`` window where it would count as
        a failure and walk an innocent replica toward quarantine (the
        stall_s sizing foot-gun the class docstring warns about).
        Failures are logged, never counted; the progress clocks reset
        afterwards so warm-up wall time cannot read as a stall."""
        for i, engine in enumerate(self.router.replicas):
            fut = None
            try:
                fut = engine.submit([self.probe_token], 1)
                fut.result(timeout=timeout_s)
            except Exception as e:   # noqa: BLE001 — warm-up only
                try:
                    if fut is not None:
                        engine._cancel(fut.request)
                except Exception:   # noqa: BLE001 — best-effort
                    pass
                self.warning("probe warm-up failed on replica %d: %s",
                             i, e)
        now = time.monotonic()
        for i in range(len(self.router.replicas)):
            self._last_progress[i] = now
            self._last_counts[i] = None
        self._warmed = True
        self.metrics.inc("health_probe_warmups")
        return self

    def start(self):
        """Start the background monitor.  Returns immediately: the
        warm-up probes run as the checker THREAD's first act (before
        any scan), so a wedged-at-boot replica delays its own
        quarantine, never the server's startup."""
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="health-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0,
                                          2 * self.probe_timeout_s))
            self._thread = None

    def _loop(self):
        if not self._warmed:
            try:
                self.warm_probes()
            except Exception as e:   # noqa: BLE001 — warm-up only
                self.warning("probe warm-up failed: %s", e)
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:   # noqa: BLE001 — prober must survive
                self.warning("health step failed: %s", e)

    # ----------------------------------------------------------- the check
    def states(self):
        """Per-replica circuit state (the gauge's source of truth)."""
        with self._lock:
            return list(self._state)

    def step(self, now=None):
        """One synchronous scan of every replica (see the class
        docstring for the state machine)."""
        now = time.monotonic() if now is None else now
        for i, engine in enumerate(self.router.replicas):
            with self._lock:
                state = self._state[i]
                reopen_at = self._reopen_at[i]
            if state == self.OPEN:
                if now >= reopen_at:
                    self._half_open_probe(i, engine, now)
                continue
            if state == self.HALF_OPEN:
                # a previous half-open probe is decided synchronously,
                # so landing here means the state was left mid-flight
                # by an exception — re-probe
                self._half_open_probe(i, engine, now)
                continue
            with self.router._lock:
                router_live = self.router._live[i]
            if not router_live:
                continue        # operator drain — not ours to manage
            m = engine.metrics
            progress = (m.counter("tokens_out")
                        + m.counter("prefill_dispatches"))
            if self._last_counts[i] is None \
                    or progress != self._last_counts[i]:
                self._last_counts[i] = progress
                self._last_progress[i] = now
            busy = (m.gauge("queue_depth", 0)
                    + m.gauge("slots_busy", 0)) > 0
            if busy:
                # staleness check: work pending but the decode loop is
                # not advancing (the EWMA's underlying facts are stale)
                failed = (now - self._last_progress[i]) > self.stall_s
            else:
                failed = not self._probe(engine)
            with self._lock:
                if failed:
                    self._fails[i] += 1
                    quarantine = self._fails[i] >= self.fail_threshold
                else:
                    self._fails[i] = 0
                    quarantine = False
            if quarantine:
                self._quarantine(i, now)

    def note_slo_page(self, i, reason="slo page", now=None):
        """An EXTERNAL page-level signal against replica ``i`` — the
        ISSUE 14 hook: the SLO monitor reports a replica whose error
        budget is burning at page rate; ``fail_threshold`` consecutive
        paging scans open the circuit through the same quarantine/
        cooldown/half-open path a failed probe takes (exactly-once
        drain semantics preserved, the half-open probe re-admits a
        recovered replica).  Counted on a DEDICATED counter: a
        slow-but-responsive replica still answers the checker's
        synthetic probes, and those successes must not reset the page
        streak (``note_slo_ok`` does, when the burn actually stops).
        Ignored for a replica already OPEN/HALF_OPEN or
        operator-drained (the checker never fights a manual
        drain)."""
        now = time.monotonic() if now is None else now
        if not 0 <= i < len(self.router.replicas):
            raise ValueError("no replica %r" % (i,))
        with self._lock:
            if self._state[i] != self.HEALTHY:
                return
        with self.router._lock:
            router_live = self.router._live[i]
        if not router_live:
            return
        self.metrics.inc("slo_page_signals")
        with self._lock:
            # this hook runs on the TELEMETRY thread while step() runs
            # on the prober's — the streak counter must not tear
            # (ISSUE 15 lint find)
            self._slo_fails[i] += 1
            streak = self._slo_fails[i]
            quarantine = streak >= self.fail_threshold
            if quarantine:
                self._slo_fails[i] = 0
        self.warning("replica %d: external SLO page signal (%s) — "
                     "%d/%d toward quarantine", i, reason,
                     streak, self.fail_threshold)
        if quarantine:
            self._quarantine(i, now)

    def note_slo_ok(self, i):
        """Clear replica ``i``'s SLO page streak — the monitor calls
        this for every mapped source NOT paging on a scan, so two
        pages separated by a healthy stretch never sum to a
        quarantine."""
        with self._lock:
            if 0 <= i < len(self._slo_fails):
                self._slo_fails[i] = 0

    def _probe(self, engine):
        """Synthetic 1-token decode against ``engine`` — bounded, and
        withdrawn on timeout so probes never pile up in a wedged
        queue.  Greedy and lane-isolated: a probe can never perturb a
        client lane's output."""
        self.metrics.inc("health_probes")
        try:
            fut = engine.submit([self.probe_token], 1)
            fut.result(timeout=self.probe_timeout_s)
            return True
        except Exception:   # noqa: BLE001 — any failure is the signal
            try:
                if "fut" in locals():
                    engine._cancel(fut.request)
            except Exception:   # noqa: BLE001 — best-effort withdrawal
                pass
            self.metrics.inc("health_probe_failures")
            return False

    # ------------------------------------------------------ state changes
    def _set_state(self, i, state):
        with self._lock:
            self._state[i] = state
        self.metrics.set_gauge("replica_health_state", state,
                               labels={"replica": str(i)})

    def _quarantine(self, i, now):
        with self._lock:
            # CLAIM the transition: the prober's step() and the
            # telemetry thread's note_slo_page() can both decide to
            # quarantine in the same window — exactly one may act, or
            # circuit_open_total double-counts one outage
            if self._state[i] != self.HEALTHY:
                return
            self._state[i] = self.OPEN
            self._fails[i] = 0
            self._reopen_at[i] = now + self._cooldown[i]
            cooldown = self._cooldown[i]
        self.metrics.set_gauge("replica_health_state", self.OPEN,
                               labels={"replica": str(i)})
        self.metrics.inc("circuit_open_total")
        self.warning("replica %d failed %d consecutive health checks: "
                     "circuit OPEN for %.1fs", i, self.fail_threshold,
                     cooldown)
        self.router.unregister(i, reason="health circuit open")

    def _half_open_probe(self, i, engine, now):
        self._set_state(i, self.HALF_OPEN)
        if self._probe(engine):
            with self._lock:
                self._cooldown[i] = self.cooldown_s
                self._fails[i] = 0
                self._slo_fails[i] = 0
            self._set_state(i, self.HEALTHY)
            self._last_counts[i] = None
            self._last_progress[i] = now
            self.info("replica %d passed the half-open probe: "
                      "re-registered", i)
            self.router.reregister(i)
        else:
            with self._lock:
                self._cooldown[i] = min(self.cooldown_cap_s,
                                        2 * self._cooldown[i])
                self._reopen_at[i] = now + self._cooldown[i]
                cooldown = self._cooldown[i]
            self._set_state(i, self.OPEN)
            self.metrics.inc("circuit_open_total")
            self.warning("replica %d failed the half-open probe: "
                         "circuit re-OPEN for %.1fs", i, cooldown)
