"""Data-parallel LM serving — N engine replicas behind a metrics-driven
router (ISSUE 8).

Tensor parallelism (``LMEngine(tp=)``) scales ONE decode stream over a
device mesh; this module adds the other serving axis: N INDEPENDENT
engine replicas — each a full :class:`~veles_tpu.serving.LMEngine`,
optionally TP-sharded over its own disjoint device slice — behind a
:class:`Router` that places each admitted request on one replica.
Replicas share nothing (no cross-replica KV, no shared queue), so
aggregate decode throughput scales with replica count while the router
keeps the serving contract intact:

- PLACEMENT is driven by the replicas' live ``serving/metrics.py``
  signals, nothing engine-internal: queue depth + busy lanes scaled by
  the replica's decode-step EWMA (its measured pace, not its nominal
  one), the TTFT EWMA as the queueing penalty, and resident-KV-page
  pressure on paged pools.  Ties (an idle fleet) break by fewest
  requests routed, so cold traffic spreads evenly instead of piling
  on replica 0.  ``policy="round_robin"`` ignores the signals — the
  skew-measurement baseline ``tools/load_gen.py`` reads against.
- ADMISSION semantics are unchanged: the router tries replicas in
  placement order and re-raises the engines' own
  :class:`~veles_tpu.serving.batcher.Overloaded` /
  :class:`~veles_tpu.serving.batcher.PoolExhausted` only when EVERY
  live replica refused (HTTP 429 upstream, same as one engine);
  deadline sheds (503) and client errors (ValueError → 400) pass
  through untouched.  A single replica degenerates to exactly today's
  one-engine path — same outputs, same errors.
- A SICK replica HOT-UNREGISTERS (:meth:`Router.unregister`): it
  leaves the placement rotation immediately and every request the
  router still has pending on it — queued or mid-decode — is
  withdrawn and REQUEUED on the surviving replicas.  A request is
  completed exactly once: a requeue only fires for work the drain
  itself interrupted (cancelled, or returned short), never for a
  result that arrived whole, and never for engine-level failures on a
  healthy replica (those keep their fault-isolation contract and fail
  to the client).  Requests never wedge: when no live replica can
  take a requeued request, its future fails loudly.

The router's own :class:`ServingMetrics` meters placement
(``routed_requests{replica="i"}`` labeled counters, ``requeued``,
rejected), and each replica's engine metrics register under one
family name with a ``{replica="i"}`` label — ``/metrics`` renders one
``# TYPE`` line per family with one row per replica, and
``/metrics.json`` (via :class:`RouterMetrics`) embeds every replica's
snapshot under ``"replicas"``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving.batcher import Overloaded
from veles_tpu.serving.metrics import ServingMetrics


def replica_device_slices(replicas, tp, devices=None):
    """The device slice each replica owns: replica ``i`` gets devices
    ``[i*tp, (i+1)*tp)`` when tensor-parallel (validated against the
    host's device count up front), one device round-robin otherwise.
    THE one replica→devices mapping — ``serve_lm`` and
    ``tools/lm_bench.py`` both consume it, so the bench measures the
    placement the server actually ships."""
    import jax
    devices = list(devices if devices is not None else jax.devices())
    n_rep = max(1, int(replicas))
    tp_n = int(tp or 0)
    if tp_n >= 2:
        if n_rep * tp_n > len(devices):
            raise ValueError(
                "replicas=%d × tp=%d needs %d devices, have %d"
                % (n_rep, tp_n, n_rep * tp_n, len(devices)))
        return [devices[i * tp_n:(i + 1) * tp_n] for i in range(n_rep)]
    return [[devices[i % len(devices)]] for i in range(n_rep)]


class RouterMetrics(ServingMetrics):
    """Router-owned metrics whose ``snapshot()`` additionally embeds
    each replica engine's snapshot under ``"replicas"`` — one
    ``/metrics.json`` fetch covers the whole fleet."""

    def __init__(self, name="lm_router", labels=None):
        super().__init__(name, labels=labels)
        self._router = None

    def snapshot(self):
        snap = super().snapshot()
        router = self._router
        if router is not None:
            snap["replicas"] = [e.metrics.snapshot()
                                for e in router.replicas]
        return snap


class _Job:
    """One routed request: the client-facing future plus the live
    engine-side placement it currently rides on."""

    __slots__ = ("prompt", "n_new", "future", "t0", "replica",
                 "engine_future", "requeue", "attempts")

    def __init__(self, prompt, n_new):
        self.prompt = prompt
        self.n_new = int(n_new)
        self.future = Future()
        self.future.job = self          # router-level cancellation handle
        self.t0 = time.monotonic()
        self.replica = None
        self.engine_future = None
        #: set by unregister() right before it withdraws the engine-side
        #: request: tells the completion callback that a cancellation or
        #: short result is drain fallout to REPLACE, not a client event
        self.requeue = False
        self.attempts = 0


class Router(Logger):
    """Place requests on ``replicas`` (started/stopped together) by
    their live metrics; see the module docstring for the contract."""

    POLICIES = ("metrics", "round_robin")

    def __init__(self, replicas, metrics=None, name="lm_router",
                 policy="metrics"):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy not in self.POLICIES:
            raise ValueError("unknown router policy %r (one of %r)"
                             % (policy, self.POLICIES))
        self.name = name
        self.replicas = replicas
        self.policy = policy
        self.metrics = metrics or ServingMetrics(name)
        if isinstance(self.metrics, RouterMetrics):
            self.metrics._router = self
        self._live = [True] * len(replicas)
        self._routed = [0] * len(replicas)
        self._pending = [set() for _ in replicas]
        self._lock = threading.Lock()
        self._rr = 0
        self._stopping = False
        self.metrics.set_gauge("replicas_total", len(replicas))
        self.metrics.set_gauge("replicas_live", len(replicas))

    # ----------------------------------------------------------- properties
    @property
    def spec_k(self):
        """Speculation headroom upstream admission must reserve — the
        replicas share a config, but take the max so a heterogeneous
        fleet still reserves enough for any placement."""
        return max(e.spec_k for e in self.replicas)

    @property
    def max_len(self):
        return min(e.max_len for e in self.replicas)

    # ------------------------------------------------------------ lifecycle
    def start(self):
        for e in self.replicas:
            e.start()
        return self

    def stop(self):
        with self._lock:
            self._stopping = True
        for e in self.replicas:
            e.stop()

    # ------------------------------------------------------------ placement
    def _score(self, i):
        """Smaller = place here.  Everything read from the replica's
        live ServingMetrics: outstanding work (queue depth + busy
        lanes) scaled by the replica's measured decode-step EWMA (a
        slow replica's queue costs more wall than a fast one's), the
        TTFT EWMA weighted by queue depth (the queueing penalty new
        arrivals actually feel), and fractional resident-KV-page
        pressure as the paged-pool tiebreak."""
        m = self.replicas[i].metrics
        depth = m.gauge("queue_depth", 0) + m.gauge("slots_busy", 0)
        step = m.ewma("decode_step", 0.0) or 1e-4
        score = depth * step + m.ewma("ttft", 0.0) * m.gauge(
            "queue_depth", 0)
        kv_total = m.gauge("kv_pages_total", 0)
        if kv_total:
            score += (1.0 - m.gauge("kv_pages_free", kv_total)
                      / kv_total) * step
        return score

    def _order(self):
        """Live replica indices, best placement first."""
        with self._lock:
            live = [i for i, ok in enumerate(self._live) if ok]
            if self.policy == "round_robin":
                self._rr += 1
                start = self._rr
            routed = list(self._routed)
        if not live:
            raise RuntimeError("router has no live replicas")
        if self.policy == "round_robin":
            return [live[(start + j) % len(live)]
                    for j in range(len(live))]
        return sorted(live, key=lambda i: (self._score(i), routed[i], i))

    def submit(self, prompt, n_new):
        """Queue one prompt on the best replica; returns a Future for
        the (n_new,) greedy continuation.  Raises exactly what one
        engine would: ValueError for client errors, Overloaded /
        PoolExhausted when every live replica refuses admission."""
        job = _Job(prompt, int(n_new))
        self._place(job)
        return job.future

    def _place(self, job):
        last_exc = None
        for i in self._order():
            engine = self.replicas[i]
            with self._lock:
                if not self._live[i]:
                    continue
            try:
                f = engine.submit(job.prompt, job.n_new)
            except Overloaded as exc:
                # queue/pool pressure on this replica: the next-best
                # may still have room (ValueError — a client error —
                # propagates immediately: it is identical on every
                # replica of a homogeneous fleet)
                last_exc = exc
                continue
            job.replica = i
            job.engine_future = f
            with self._lock:
                # re-check liveness at COMMIT: a drain that ran between
                # the pre-submit check and here already snapshotted
                # _pending[i] without this job, so committing would
                # strand it on the drained replica — withdraw and keep
                # looking instead
                stale = not self._live[i]
                if not stale:
                    self._pending[i].add(job)
                    self._routed[i] += 1
            if stale:
                engine._cancel(f.request)
                job.engine_future = None
                job.replica = None
                continue
            self.metrics.record_enqueue()
            self.metrics.inc("routed_requests",
                             labels={"replica": str(i)})
            f.add_done_callback(
                lambda f, job=job, i=i: self._on_engine_done(job, i, f))
            return
        self.metrics.record_reject()
        raise last_exc if last_exc is not None else Overloaded()

    # ----------------------------------------------------------- completion
    def _on_engine_done(self, job, i, engine_future):
        """Runs on the replica's worker (or canceller) thread when the
        engine-side future settles.  Exactly-once delivery: the
        router future is resolved here and only here, and a requeue
        fires only for drain fallout (see _Job.requeue)."""
        with self._lock:
            self._pending[i].discard(job)
            live = self._live[i]
            stopping = self._stopping
        if job.future.done():            # withdrawn at the router level
            return
        requeue = job.requeue and not stopping
        if engine_future.cancelled():
            # withdrawn before any decode: drain fallout replaces it,
            # a router-level cancellation stays cancelled
            if requeue:
                self._replace(job)
            else:
                job.future.cancel()
            return
        exc = engine_future.exception()
        if exc is not None:
            from veles_tpu.serving.batcher import DeadlineExceeded
            if (requeue or not live) and not stopping \
                    and not isinstance(exc, (Overloaded,
                                             DeadlineExceeded)):
                # in-flight work dying WITH its drained/sick replica
                # (engine stopped, poisoned step) is the router's
                # problem; on a live replica the engine's
                # fault-isolation contract stands and the client sees
                # the fault
                self._replace(job)
                return
            job.future.set_exception(exc)
            return
        result = engine_future.result()
        if requeue and len(result) < job.n_new:
            # the drain interrupted this lane mid-decode: the engine
            # resolved it with the tokens it had (its cancellation
            # path) — rerun the request whole on a live replica
            self._replace(job)
            return
        self.metrics.record_response(time.monotonic() - job.t0)
        job.future.set_result(result)

    def _replace(self, job):
        """Re-place a drain-interrupted job on the surviving replicas —
        or fail it loudly when none can take it (never wedge)."""
        if job.future.done():
            # raced a router-level cancellation (generate() sibling
            # withdrawal): nobody reads this result — do not spend a
            # healthy replica's slots rerunning it
            return
        job.requeue = False
        job.attempts += 1
        self.metrics.inc("requeued_requests")
        if job.attempts > len(self.replicas) + 1:
            job.future.set_exception(RuntimeError(
                "request could not be re-placed after %d drain retries"
                % job.attempts))
            return
        try:
            self._place(job)
        except Exception as exc:   # noqa: BLE001 — delivered, not raised
            if not job.future.done():
                job.future.set_exception(exc)

    # --------------------------------------------------------------- client
    def generate(self, prompts, n_new, return_replicas=False):
        """Decode a (b, s) prompt batch across the fleet; returns
        (b, s + n_new) int32 (and, with ``return_replicas``, the
        replica index that served each row).  All-or-nothing sibling
        cancellation, exactly like ``LMEngine.generate``."""
        prompts = numpy.asarray(prompts, numpy.int32)
        futures = []
        try:
            for row in prompts:
                futures.append(self.submit(row, n_new))
            news = numpy.stack([f.result() for f in futures])
        except Exception:
            for f in futures:
                self.cancel(f)
            raise
        out = numpy.concatenate([prompts, news], axis=1)
        if return_replicas:
            return out, [f.job.replica for f in futures]
        return out

    def cancel(self, future):
        """Withdraw a routed request (sibling cancellation): the
        engine-side request is cancelled and the router future will
        NOT be re-placed."""
        job = future.job
        job.requeue = False
        with self._lock:
            engine_future = job.engine_future
            i = job.replica
        if engine_future is not None:
            self.replicas[i]._cancel(engine_future.request)
        future.cancel()

    # ---------------------------------------------------------------- drain
    def unregister(self, i, reason="sick"):
        """Hot-unregister replica ``i``: it leaves the placement
        rotation NOW, and every request the router still has pending
        on it is withdrawn and re-placed on the surviving replicas
        (queued requests requeue unserved; a mid-decode lane is
        cancelled and its request reruns whole elsewhere — no loss,
        no duplicate completion).  The engine itself keeps running —
        the caller decides whether to stop or restart it; re-admit
        with :meth:`reregister`.  Returns the number of requests
        withdrawn."""
        with self._lock:
            if not self._live[i]:
                return 0
            self._live[i] = False
            jobs = list(self._pending[i])
            live_now = sum(1 for ok in self._live if ok)
        self.metrics.set_gauge("replicas_live", live_now)
        self.metrics.inc("replica_drains")
        self.warning("draining replica %d (%s): re-placing %d pending "
                     "request(s) on %d live replica(s)",
                     i, reason, len(jobs), live_now)
        engine = self.replicas[i]
        for job in jobs:
            job.requeue = True
            engine._cancel(job.engine_future.request)
        return len(jobs)

    def reregister(self, i):
        """Return a drained replica to the placement rotation (after a
        restart or recovery)."""
        with self._lock:
            self._live[i] = True
            live_now = sum(1 for ok in self._live if ok)
        self.metrics.set_gauge("replicas_live", live_now)

    # ------------------------------------------------------------- evidence
    def routed_counts(self):
        """Requests placed per replica (including requeues) — the
        server-side balance evidence the bench records."""
        with self._lock:
            return list(self._routed)
