"""Deterministic fault injection for the serving tier (ISSUE 10).

The serving stack's failure paths — worker fault isolation, pool
cleanup on a mid-prefill error, router draining, admission storms —
were until now exercised only by ad-hoc monkeypatching in tests.  This
module makes faults a FIRST-CLASS, deterministic input: a
:class:`FaultPlan` arms named SITES (fixed strings compiled into
``lm_engine.py`` / ``batcher.py`` / ``router.py`` / ``restful_api.py``)
with rules that raise, delay, or freeze at chosen call numbers, and the
chaos harness (``tools/chaos_bench.py`` / ``tools/chaos_smoke.py``)
drives the health/retry/recovery subsystems against it.

Design rules:

- UNARMED IS FREE.  Engines hold ``self._faults = None`` by default and
  every site is one attribute-is-None check — no dict lookup, no lock,
  no counter.  The fault layer costs nothing unless a plan is armed
  (the ``fault_free_overhead`` chaos-bench leg pins this).
- DETERMINISTIC.  Rules fire on per-site CALL NUMBERS (``calls={3}``,
  ``every=4``, ``after=10``) counted under the plan's lock, so a given
  plan against a given request order always injects at the same
  dispatches.  ``prob=`` draws from the plan's own seeded RandomState —
  reproducible for a fixed call order, never ambient randomness.
- INJECTED ERRORS ARE LABELED.  The default exception is
  :class:`InjectedFault`; logs and asserts can always tell an injected
  fault from a real one.
- FREEZES ARE RELEASABLE.  ``kind="freeze"`` blocks the calling thread
  (a wedged replica: the worker stops ticking, queues grow, the health
  prober must notice) on an Event that :meth:`FaultPlan.release` sets —
  tests and the bench always thaw before teardown, so a frozen engine
  can still ``stop()``.

Sites (each a no-op when unarmed):

===================== ==================================================
``engine.submit``     LMEngine.submit admission (PoolExhausted storms)
``engine.tick``       top of the engine worker loop (latency / freeze)
``engine.prefill``    whole-prompt prefill dispatch
``engine.chunk``      chunked-prefill dispatch (contiguous and paged)
``engine.cow``        paged copy-on-write page-copy dispatch
``engine.step``       batched decode-step dispatch
``engine.verify``     speculative verify dispatch
``engine.swap``       weight-swap apply (LMEngine.swap_weights; a
                      raised fault refuses the swap, old weights
                      keep serving — the bad-canary chaos shape)
``batcher.submit``    MicroBatcher.submit admission
``batcher.dispatch``  MicroBatcher forward dispatch
``router.place``      Router placement, per replica attempt
``http.request``      restful_api request dispatch (transient HTTP
                      errors via :class:`InjectedHTTPError`, latency)
===================== ==================================================

Plans load from JSON (CLI ``--fault-plan plan.json``)::

    {"seed": 7, "sites": [
        {"site": "engine.step", "kind": "error", "calls": [3],
         "exc": "InjectedFault"},
        {"site": "engine.tick", "kind": "latency", "every": 8,
         "latency_s": 0.05},
        {"site": "http.request", "kind": "error", "exc": "http_503",
         "prob": 0.1, "times": 5}]}
"""

from __future__ import annotations

import json
import threading
import time

import numpy

from veles_tpu.serving import lockcheck


class InjectedFault(RuntimeError):
    """An exception the fault layer raised on purpose — never confusable
    with a real device/driver error in logs or test asserts."""


class InjectedHTTPError(RuntimeError):
    """A transient HTTP-level fault: ``restful_api`` serves ``code``
    with a structured body (and ``Retry-After`` on 429/503) instead of
    treating it as a real 500 — the shape retryable infrastructure
    blips (LB resets, proxy timeouts) have in production."""

    def __init__(self, code=503, retry_after=1.0):
        super().__init__("injected transient HTTP %d" % code)
        self.code = int(code)
        self.retry_after = float(retry_after)


def _named_exc(name):
    """Exception factory for JSON plans: a few serving-meaningful names
    plus the generic labeled fault."""
    def overloaded(msg):
        from veles_tpu.serving.batcher import Overloaded
        return Overloaded()

    def pool_exhausted(msg):
        from veles_tpu.serving.batcher import PoolExhausted
        return PoolExhausted(1, 0)

    table = {
        "InjectedFault": InjectedFault,
        "RuntimeError": RuntimeError,
        "Overloaded": overloaded,
        "PoolExhausted": pool_exhausted,
        "http_429": lambda msg: InjectedHTTPError(429, 0.25),
        "http_500": lambda msg: InjectedHTTPError(500),
        "http_503": lambda msg: InjectedHTTPError(503),
    }
    if name not in table:
        raise ValueError("unknown fault exception %r (one of %r)"
                         % (name, sorted(table)))
    return table[name]


class _Rule:
    __slots__ = ("kind", "make_exc", "message", "calls", "every",
                 "after", "prob", "times", "latency_s", "duration_s",
                 "fired")

    def __init__(self, kind, make_exc, message, calls, every, after,
                 prob, times, latency_s, duration_s):
        self.kind = kind
        self.make_exc = make_exc
        self.message = message
        self.calls = frozenset(calls) if calls is not None else None
        self.every = every
        self.after = after
        self.prob = prob
        self.times = times
        self.latency_s = latency_s
        self.duration_s = duration_s
        self.fired = 0


class FaultPlan:
    """A seeded set of fault rules over named sites; see the module
    docstring.  Thread-safe: counters and the RNG live under one lock
    (sites only pay it once ARMED — unarmed engines never call in)."""

    KINDS = ("error", "latency", "freeze")

    #: lock-discipline map (ISSUE 15): rules/counters/RNG are touched
    #: from every armed site's thread — one plan lock guards them all.
    _guarded_by = {
        "_rules": "_lock",
        "_counts": "_lock",
        "_fired": "_lock",
        "_rng": "_lock",
    }

    def __init__(self, seed=0):
        self._rules = {}        # site -> [_Rule]
        self._counts = {}       # site -> calls observed
        self._fired = {}        # site -> rules fired
        self._lock = lockcheck.make_lock("faults._lock")
        self._rng = numpy.random.RandomState(seed)
        #: set by release(): every current AND future freeze is a no-op
        #: (teardown must always be able to thaw a wedged worker)
        self._released = threading.Event()

    # -------------------------------------------------------------- arming
    def arm(self, site, kind="error", exc=None, message=None,
            calls=None, every=None, after=None, prob=None, times=None,
            latency_s=0.05, duration_s=600.0):
        """Add one rule at ``site``.  Conditions given are ANDed
        (``calls`` membership, ``every`` N-th call, ``after`` a call
        threshold, ``prob`` a seeded coin); no condition = every call.
        ``times`` caps total firings.  ``kind``: 'error' raises
        (``exc`` = class, factory, or JSON name; default
        InjectedFault), 'latency' sleeps ``latency_s``, 'freeze'
        blocks until :meth:`release` (at most ``duration_s``).
        Returns self (chainable)."""
        if kind not in self.KINDS:
            raise ValueError("fault kind %r (one of %r)"
                             % (kind, self.KINDS))
        if isinstance(exc, str):
            exc = _named_exc(exc)
        if exc is None:
            exc = InjectedFault
        rule = _Rule(kind, exc,
                     message or ("injected %s at %s" % (kind, site)),
                     calls, every, after, prob, times,
                     float(latency_s), float(duration_s))
        with self._lock:
            self._rules.setdefault(site, []).append(rule)
        return self

    def disarm(self, site=None):
        """Drop every rule (or just ``site``'s) — later calls are
        no-ops again; call counters survive for evidence reads."""
        with self._lock:
            if site is None:
                self._rules.clear()
            else:
                self._rules.pop(site, None)

    def release(self):
        """Thaw every freeze, present and future — MUST be called
        before stopping an engine a freeze rule wedged."""
        self._released.set()

    # -------------------------------------------------------------- firing
    def fire(self, site):
        """Evaluate ``site``'s rules at this call.  Called only from
        the compiled-in hooks (which already checked a plan is
        attached); raises / sleeps / blocks per the matching rules."""
        todo = []
        with self._lock:
            rules = self._rules.get(site)
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            for r in rules or ():
                if r.times is not None and r.fired >= r.times:
                    continue
                if r.calls is not None and n not in r.calls:
                    continue
                if r.every is not None and n % r.every:
                    continue
                if r.after is not None and n <= r.after:
                    continue
                if r.prob is not None \
                        and self._rng.random_sample() >= r.prob:
                    continue
                r.fired += 1
                self._fired[site] = self._fired.get(site, 0) + 1
                todo.append(r)
        for r in todo:
            if r.kind == "latency":
                time.sleep(r.latency_s)
            elif r.kind == "freeze":
                self._released.wait(r.duration_s)
            else:
                raise r.make_exc(r.message)

    # ------------------------------------------------------------ evidence
    def calls(self, site):
        """Calls observed at ``site`` (armed or not, once fire ran)."""
        with self._lock:
            return self._counts.get(site, 0)

    def fired(self, site=None):
        """Rules fired at ``site`` — or the whole {site: count} map."""
        with self._lock:
            if site is not None:
                return self._fired.get(site, 0)
            return dict(self._fired)

    # --------------------------------------------------------------- specs
    @classmethod
    def from_spec(cls, spec):
        """Build a plan from a JSON-shaped dict: ``{"seed": S,
        "sites": [{"site": ..., "kind": ..., ...}, ...]}``."""
        plan = cls(seed=int(spec.get("seed", 0)))
        for entry in spec.get("sites", ()):
            entry = dict(entry)
            site = entry.pop("site")
            plan.arm(site, **entry)
        return plan

    @classmethod
    def from_file(cls, path):
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_spec(json.load(f))
