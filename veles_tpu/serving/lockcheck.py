"""Lock-order witness — runtime concurrency checking for the serving
tier (ISSUE 15).

The serving stack holds a dozen locks across ten modules, and the
rules that keep them deadlock-free ("health before router", "never
hold a lock across a device dispatch") lived only in docstrings.  This
module makes them checkable at runtime, the ``faults.py`` way:

- UNARMED IS ONE NONE-CHECK.  Serving locks are built through
  :func:`make_lock` / :func:`make_condition`, thin wrappers whose
  acquire/release cost, when no witness is armed, is a module-global
  ``_witness is None`` check on top of the real ``threading``
  primitive.  The chaos bench's ``fault_free_overhead`` leg pins the
  shim inside the existing <2%-of-a-decode-step bound.
- ARMED IN TESTS.  ``tests/conftest.py`` arms a
  :class:`LockOrderWitness` around the serving suites
  (``test_serving`` / ``test_kv_pool`` / ``test_tracing`` /
  ``test_timeseries``): every acquisition records an edge
  ``held-lock → acquired-lock`` in a global lock-order graph, every
  NEW edge runs a cycle check, and the engines' dispatch sites call
  :func:`note_dispatch` so a lock held while a jitted program (or
  ``block_until_ready`` fence) runs is caught too.  Violations carry
  BOTH stacks — where the held lock was taken and where the conflict
  happened — and the arming fixture fails the test loudly on any.

Lock IDENTITY is two-level: edges are keyed by ROLE (the name passed
to the factory, e.g. ``"router._lock"``), so the order rule learned
from replica 0 protects replica 1; re-entrancy is tracked per
INSTANCE, so holding two engines' ``_cond`` at once is a self-edge
cycle (a real hazard) while a Condition's internal re-acquire after
``wait()`` is not.

The static half of ISSUE 15 — which attribute needs which lock —
lives in ``tools/veles_lint.py``; see USAGE.md "Static analysis and
concurrency checks".
"""

from __future__ import annotations

import sys
import threading

#: the armed witness (None = every shim is a single None-check)
_witness = None

#: sites that put work on the DEVICE: a tracked lock held while one of
#: these runs serializes every other thread behind device wall time —
#: the lock-held-across-dispatch class of bug the witness flags
DISPATCH_SITES = frozenset((
    "engine.prefill", "engine.chunk", "engine.cow", "engine.step",
    "engine.verify", "engine.fence", "batcher.dispatch",
))


class LockOrderViolation(AssertionError):
    """A lock-order cycle or a lock held across a device dispatch —
    raised by tests that opt in, and always recorded on the witness's
    ``violations`` list (the arming fixture asserts it empty)."""


def arm(witness):
    """Install ``witness`` globally; returns it.  Tracked locks start
    reporting on their next acquisition — arm BEFORE building the
    engines under test only if you want construction covered too."""
    global _witness
    _witness = witness
    return witness


def disarm():
    """Remove the armed witness (shims fall back to the None-check)."""
    global _witness
    _witness = None


def armed():
    return _witness


def note_dispatch(site):
    """Device-dispatch hook for code not using the engines' built-in
    ``_fault`` sites — one None-check when unarmed.  (The serving hot
    paths — ``lm_engine._fault``/``_tfence``, ``batcher._dispatch`` —
    deliberately inline the ``lockcheck._witness is not None`` check
    instead of calling here: an attribute test with no function call
    is the unarmed-is-free discipline those sites are bound to.)"""
    w = _witness
    if w is not None:
        w.dispatch(site)


def _stack(skip=2, limit=8):
    """A compact (file, line, function) stack for violation evidence —
    ``sys._getframe`` walk, formatted lazily (armed-path cost only)."""
    frames = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ()
    while f is not None and len(frames) < limit:
        code = f.f_code
        frames.append((code.co_filename, f.f_lineno, code.co_name))
        f = f.f_back
    return tuple(frames)


def _fmt_stack(frames):
    if not frames:
        return "    <no stack captured>"
    return "\n".join("    %s:%d in %s" % fr for fr in frames)


class LockOrderWitness:
    """Records the per-thread lock-acquisition graph and flags
    ordering cycles (potential deadlocks) and locks held across device
    dispatches; see the module docstring.  ``raise_on_violation``
    additionally raises :class:`LockOrderViolation` at the detection
    point (tests asserting a deliberate inversion); either way every
    violation lands on ``violations`` with both stacks."""

    def __init__(self, name="lock-witness", raise_on_violation=False,
                 max_violations=32):
        self.name = name
        self.raise_on_violation = bool(raise_on_violation)
        self.max_violations = int(max_violations)
        #: formatted violation reports (the arming fixture's assert)
        self.violations = []
        self.acquisitions = 0
        self.dispatch_checks = 0
        self._tls = threading.local()
        #: role -> set of roles acquired while holding it, plus the
        #: first-observed stacks per edge (evidence for the report).
        #: Guarded by _meta — a RAW lock, deliberately outside the
        #: tracked system (the witness must never witness itself).
        self._edges = {}         # role -> {role}
        self._edge_ev = {}       # (a, b) -> (stack_holding_a, stack_b)
        self._meta = threading.Lock()

    # -------------------------------------------------------------- held
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_roles(self):
        """The calling thread's held lock roles, outermost first."""
        return [role for _, role, _ in self._held()]

    # -------------------------------------------------------- violations
    def _violate(self, report):
        with self._meta:
            if len(self.violations) < self.max_violations:
                self.violations.append(report)
        if self.raise_on_violation:
            raise LockOrderViolation(report)

    # ------------------------------------------------------ acquisition
    def before_acquire(self, lock):
        """Called by a tracked lock before blocking on the primitive:
        adds ``held → lock`` edges and cycle-checks every new one (the
        potential deadlock is flagged even when this run's interleaving
        never actually deadlocks)."""
        held = self._held()
        self.acquisitions += 1
        if not held:
            return
        stk = None
        for inst, role, inst_stk in held:
            if inst is lock:
                if not lock._reentrant:
                    self._violate(
                        "re-acquire of non-reentrant lock %r already "
                        "held by this thread (self-deadlock)\n"
                        "  first acquired at:\n%s\n  re-acquired at:\n%s"
                        % (lock.name, _fmt_stack(inst_stk),
                           _fmt_stack(_stack(3))))
                continue
            if role == lock.name:
                # two INSTANCES of one role held together (two engines'
                # _cond, two metrics' _lock): a self-edge cycle
                self._violate(
                    "two %r instances held by one thread (instance "
                    "self-cycle)\n  first acquired at:\n%s\n"
                    "  second acquired at:\n%s"
                    % (lock.name, _fmt_stack(inst_stk),
                       _fmt_stack(_stack(3))))
                continue
            edge = (role, lock.name)
            with self._meta:
                known = lock.name in self._edges.get(role, ())
                if not known:
                    if stk is None:
                        stk = _stack(3)
                    self._edges.setdefault(role, set()).add(lock.name)
                    self._edge_ev[edge] = (inst_stk, stk)
                    cycle = self._find_path(lock.name, role)
                else:
                    cycle = None
            if cycle:
                path = [lock.name] + cycle
                ev = []
                for a, b in zip(path, path[1:]):
                    ha, hb = self._edge_ev.get(
                        (a, b), ((), ()))
                    ev.append("  edge %s -> %s:\n   holding %s at:\n%s"
                              "\n   acquiring %s at:\n%s"
                              % (a, b, a, _fmt_stack(ha), b,
                                 _fmt_stack(hb)))
                self._violate(
                    "lock-order cycle: %s (acquiring %r while holding "
                    "%r closes the loop)\n"
                    "  holding %s at:\n%s\n  acquiring %s at:\n%s\n%s"
                    % (" -> ".join(path + [path[0]]), lock.name, role,
                       role, _fmt_stack(inst_stk), lock.name,
                       _fmt_stack(stk if stk is not None
                                  else _stack(3)),
                       "\n".join(ev)))

    def _find_path(self, src, dst):
        """DFS ``src -> ... -> dst`` over the edge graph (meta lock
        held).  Returns the role path src..dst, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def after_acquire(self, lock, reacquire=False):
        """The primitive is now held: push it on the thread's stack.
        ``reacquire`` marks a Condition re-taking its lock after
        ``wait()`` — no new edges (they were recorded at the original
        acquire).  The held-entry evidence is ONE caller frame — full
        stacks are captured only at violation/new-edge time, so the
        armed per-acquisition cost stays a getframe + an append (the
        serving suites cross this millions of times per run)."""
        if reacquire:
            self._held().append((lock, lock.name, ()))
            return
        f = sys._getframe(2)
        code = f.f_code
        self._held().append((lock, lock.name,
                             ((code.co_filename, f.f_lineno,
                               code.co_name),)))

    def on_release(self, lock):
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    # ---------------------------------------------------------- dispatch
    def dispatch(self, site):
        """A device dispatch (or fence) at ``site``: no tracked lock
        may be held — a held lock would serialize every other thread
        behind device wall time, and on a wedged device, forever."""
        if site not in DISPATCH_SITES:
            return
        self.dispatch_checks += 1
        held = self._held()
        if held:
            inst, role, stk = held[-1]
            self._violate(
                "lock %r held across device dispatch %r\n"
                "  lock acquired at:\n%s\n  dispatch at:\n%s"
                % (role, site, _fmt_stack(stk),
                   _fmt_stack(_stack(3))))

    # ------------------------------------------------------------ report
    def report(self):
        with self._meta:
            return {"name": self.name,
                    "acquisitions": self.acquisitions,
                    "dispatch_checks": self.dispatch_checks,
                    "edges": {a: sorted(bs)
                              for a, bs in sorted(self._edges.items())},
                    "violations": list(self.violations)}


class TrackedLock:
    """``threading.Lock`` with the witness shim — non-reentrant, so a
    same-thread re-acquire is itself reported (it would deadlock)."""

    __slots__ = ("_lock", "name")
    _reentrant = False

    def __init__(self, name):
        self._lock = threading.Lock()
        self.name = name

    def acquire(self, blocking=True, timeout=-1):
        w = _witness
        if w is not None:
            w.before_acquire(self)
        got = self._lock.acquire(blocking, timeout)
        if got and _witness is not None:
            _witness.after_acquire(self)
        return got

    def release(self):
        if _witness is not None:
            _witness.on_release(self)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        w = _witness
        if w is not None:
            w.before_acquire(self)
        self._lock.acquire()
        if _witness is not None:
            _witness.after_acquire(self)
        return self

    def __exit__(self, *exc):
        if _witness is not None:
            _witness.on_release(self)
        self._lock.release()
        return False


class TrackedCondition:
    """``threading.Condition`` with the witness shim.  The underlying
    lock is the Condition's own RLock, so the wrapper is re-entrant
    like the primitive; ``wait()`` pops the held entry for its sleep
    and re-pushes on wake (edge-free — the order was recorded at the
    original acquire)."""

    __slots__ = ("_cond", "name")
    _reentrant = True

    def __init__(self, name):
        self._cond = threading.Condition()
        self.name = name

    def __enter__(self):
        w = _witness
        if w is not None:
            w.before_acquire(self)
        self._cond.__enter__()
        if _witness is not None:
            _witness.after_acquire(self)
        return self

    def __exit__(self, *exc):
        if _witness is not None:
            _witness.on_release(self)
        return self._cond.__exit__(*exc)

    def wait(self, timeout=None):
        w = _witness
        if w is not None:
            w.on_release(self)
        try:
            return self._cond.wait(timeout)
        finally:
            if _witness is not None:
                _witness.after_acquire(self, reacquire=True)

    def wait_for(self, predicate, timeout=None):
        w = _witness
        if w is not None:
            w.on_release(self)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if _witness is not None:
                _witness.after_acquire(self, reacquire=True)

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()


def make_lock(name):
    """A serving-tier mutex: witness-tracked under ``name`` when a
    witness is armed, a plain fast lock otherwise (the wrapper's
    unarmed cost is one module-global None-check per operation)."""
    return TrackedLock(name)


def make_condition(name):
    """A serving-tier condition variable, same discipline."""
    return TrackedCondition(name)
