"""Continuous telemetry — a bounded time-series store over the serving
metrics (ISSUE 14).

``serving/metrics.py`` answers "what is the value NOW" and PR 12's
tracer answers "where did THIS request's milliseconds go"; nothing
answered "how has the fleet behaved over the last five minutes and is
that within objective" — the signal shape both SLO burn-rate alerting
(``serving/slo.py``) and the ROADMAP's cost-model autotuning need.
This module adds it without touching the hot path at all: a
:class:`TimeSeriesStore` PULLS a snapshot of every registered
:class:`~veles_tpu.serving.metrics.ServingMetrics` source on a
background cadence (default 1 s) and keeps each family in a bounded
ring of ``(t, value)`` points:

- COUNTERS (requests, errors, tokens_out, every named counter) keep
  their cumulative value per sample; :meth:`TimeSeriesStore.window`
  turns them into windowed RATES with restart-tolerant deltas (a
  counter that went backwards — a replaced engine — contributes zero,
  never a negative rate).
- GAUGES (queue_depth, slots_busy, kv_pages_free, every runtime gauge
  below) keep the sampled value; a window read returns
  last/min/max/mean.
- HISTOGRAMS (ttft, decode_step, latency, queue_wait, batch_size)
  keep (count, sum, cumulative-bucket) tuples; a window read computes
  the DELTA histogram over the window and resolves p50/p95 from the
  bucket bounds — live tail latency without retaining samples.

RUNTIME / DEVICE GAUGES ride the same store: :func:`runtime_probe`
runs at the top of every sampling tick and writes into the engine's
own ServingMetrics (so ``/metrics[.json]`` carries them too):
``compile_programs`` (the live jit program-cache size the invariant
tests pin) + a monotone ``compiles_total`` counter, process RSS,
``jax`` device memory where the backend reports it, live MFU from the
lm_bench per-leg FLOPs model (:func:`decode_flops_per_token` lives
here now; ``tools/lm_bench.py`` imports it), and the megastep waste
fraction.

DISCIPLINE (the ``faults.py``/``tracing.py`` rule): the serving hot
path has ZERO telemetry sites — the store samples from its own
thread, engines never call in.  The armed sampler's cost is one
``sample_once()`` per ``interval_s`` of wall clock, measured and
bounded (<1% of a decode step together with the tracer's incremental
ledger) by the chaos bench's ``fault_free_overhead`` leg.

Consumers: ``GET /timeseries.json?window=S`` (strict JSON, stamped
with the shared monotonic ``sampled_at`` offset), ``serving/slo.py``
burn-rate evaluation via :meth:`window`, and
``tools/slo_report.py`` timelines from a captured export.
``sample_once()`` is public and synchronous so tests and the chaos
harness drive the cadence deterministically.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck
from veles_tpu.serving.metrics import ServingMetrics, monotonic_offset

#: advertised peak FLOPs by TPU device kind (bf16 matmul peak — the
#: MFU denominator convention; fp32 serving reads lower, which only
#: makes the reported MFU conservative).  Overridable via
#: VELES_PEAK_FLOPS for new silicon or calibrated CPU baselines.
TPU_PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v4", 275e12), ("v6", 918e12),
)
#: nominal single-core CPU matmul ceiling — keeps the MFU column
#: well-defined (and honestly tiny) on CPU runs; real MFU claims come
#: from TPU sessions (docs/PERF.md)
CPU_NOMINAL_FLOPS = 1e11


def peak_flops_estimate():
    """(peak_flops, source_label) for the MFU denominator: the env
    override wins, then the TPU device-kind table, then the CPU
    nominal.  The label travels in every record so a reader can tell a
    calibrated number from a nominal one."""
    import jax
    env = os.environ.get("VELES_PEAK_FLOPS")
    if env:
        return float(env), "env:VELES_PEAK_FLOPS"
    from veles_tpu.ops.pallas_kernels import on_tpu
    if on_tpu():
        kind = jax.devices()[0].device_kind.lower()
        for name, peak in TPU_PEAK_FLOPS:
            if name in kind:
                return peak, "tpu:%s" % name
        return 197e12, "tpu:unknown-kind-default"
    return CPU_NOMINAL_FLOPS, "cpu:nominal"


def decode_flops_per_token(vocab, d_model, n_layers, ctx,
                           n_heads=4, kv_heads=None, d_ff=None):
    """Model FLOPs one KV-cached greedy token costs (forward only):
    the qkvo projections, FFN and head matmuls plus the two attention
    matmuls against ``ctx`` resident rows — the numerator of the MFU
    column (matmul FLOPs only; layernorms/softmax are noise at these
    widths).  THE one FLOPs-per-token model: ``tools/lm_bench.py``'s
    per-leg MFU and the live ``mfu_live`` gauge both read it."""
    kv = kv_heads or n_heads
    d_kv = d_model // n_heads * kv
    d_ff = d_ff or 4 * d_model
    proj = 2 * d_model * (2 * d_model + 2 * d_kv)      # wq, wo, wk, wv
    ffn = 4 * d_model * d_ff
    attn = 4 * ctx * d_model                           # q·K + p·V
    head = 2 * d_model * vocab
    return n_layers * (proj + ffn + attn) + head


def engine_flops_per_token(engine, ctx=None):
    """The FLOPs model read off a live :class:`LMEngine`'s param tree
    (``ctx`` defaults to half the cache — the mid-decode nominal)."""
    params = engine.params
    embed = params["embed"]
    vocab, d_model = int(embed.shape[0]), int(embed.shape[1])
    head_dim = d_model // engine.n_heads
    blk0 = params["blocks"][0]
    kv_heads = int(blk0["attn"]["wk"].shape[1]) // head_dim
    d_ff = int(blk0["w1"].shape[1]) if "w1" in blk0 else None
    return decode_flops_per_token(
        vocab, d_model, len(params["blocks"]),
        ctx if ctx is not None else engine.max_len // 2,
        n_heads=engine.n_heads, kv_heads=kv_heads, d_ff=d_ff)


def engine_program_cache_size(engine):
    """The engine's LIVE compiled-program count: the sum of every jit
    family's ``_cache_size()`` — the number the jit-guard tests pin,
    now readable as a gauge while serving.  Tolerant of monkeypatched
    families (test gear replaces ``_step_jit`` with a plain callable)
    and of jaxlibs without the introspection hook."""
    total = 0
    for attr in ("_prefill_jit", "_install_jit", "_step_jit",
                 "_chunk_jit", "_chunk_install_jit",
                 "_chunk_extract_jit", "_verify_jit", "_page_copy_jit",
                 "_megastep_jit"):
        fn = getattr(engine, attr, None)
        size = getattr(fn, "_cache_size", None)
        if size is None:
            continue
        try:
            total += int(size())
        except Exception:   # noqa: BLE001 — introspection-only
            pass
    return total


def _process_rss_bytes():
    """Resident set size of THIS process (bytes) — /proc on Linux,
    getrusage elsewhere; 0 when neither works."""
    try:
        with open("/proc/self/statm", "r", encoding="ascii") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:   # noqa: BLE001 — platform fallback
        pass
    try:
        import resource
        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss_kb) * 1024
    except Exception:   # noqa: BLE001 — best-effort gauge
        return 0


def _device_mem_bytes(engine):
    """Sum of ``bytes_in_use`` over the engine's devices, or None when
    the backend does not report memory stats (CPU jaxlibs mostly
    don't)."""
    try:
        import jax
        if engine._mesh is not None:
            devices = list(engine._mesh.devices.flat)
        elif engine._device is not None:
            devices = [engine._device]
        else:
            devices = [jax.devices()[0]]
        total, seen = 0, False
        for d in devices:
            stats = getattr(d, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                seen = True
        return total if seen else None
    except Exception:   # noqa: BLE001 — best-effort gauge
        return None


def runtime_probe(engine, flops_per_token=None, peak_flops=None,
                  clock=time.monotonic):
    """A per-tick probe closure for ``engine`` writing the ISSUE 14
    runtime/device gauges into the engine's own ServingMetrics (so
    they ride ``/metrics[.json]`` AND the store's rings):

    - ``compile_programs`` gauge — live jit program-cache size (the
      jit-guard invariant as a continuously-observable signal) and the
      monotone ``compiles_total`` counter (its positive deltas);
    - ``process_rss_bytes`` gauge;
    - ``device_mem_bytes`` gauge where the backend reports it;
    - ``tokens_per_s`` + ``mfu_live`` gauges — tokens_out rate between
      probes times the lm_bench FLOPs model over the platform peak;
    - ``megastep_waste_frac`` gauge — wasted/lane iterations between
      probes (the fused-decode early-exit tail, live).
    """
    if flops_per_token is None:
        try:
            flops_per_token = engine_flops_per_token(engine)
        except Exception:   # noqa: BLE001 — MFU gauge is optional
            flops_per_token = None
    if peak_flops is None and flops_per_token is not None:
        peak_flops = peak_flops_estimate()[0]
    state = {"t": None, "tokens": 0, "programs": 0,
             "ms_lane": 0, "ms_waste": 0}

    def probe():
        m = engine.metrics
        now = clock()
        programs = engine_program_cache_size(engine)
        m.set_gauge("compile_programs", programs)
        if programs > state["programs"]:
            m.inc("compiles_total", programs - state["programs"])
            state["programs"] = programs
        m.set_gauge("process_rss_bytes", _process_rss_bytes())
        dev = _device_mem_bytes(engine)
        if dev is not None:
            m.set_gauge("device_mem_bytes", dev)
        tokens = m.counter("tokens_out")
        if state["t"] is not None and now > state["t"]:
            rate = max(0, tokens - state["tokens"]) / (now - state["t"])
            m.set_gauge("tokens_per_s", round(rate, 3))
            if flops_per_token and peak_flops:
                m.set_gauge("mfu_live",
                            round(rate * flops_per_token / peak_flops,
                                  8))
        lane = m.counter("megastep_lane_iterations")
        waste = m.counter("megastep_wasted_iterations")
        d_lane = lane - state["ms_lane"]
        d_waste = waste - state["ms_waste"]
        if d_lane > 0:
            m.set_gauge("megastep_waste_frac",
                        round(d_waste / d_lane, 6))
        state.update(t=now, tokens=tokens, ms_lane=lane,
                     ms_waste=waste)

    return probe


def _finite(v):
    """Strict-JSON guard: NaN/Infinity become None (strict parsers
    reject them)."""
    if isinstance(v, float) and not math.isfinite(v):
        return None
    return v


class _Series:
    """One bounded ring of (t, value) points.  ``kind`` fixes the
    window() semantics; histogram points hold (count, sum, cum-bucket
    tuple) and carry the bound list once."""

    __slots__ = ("kind", "points", "bounds")

    def __init__(self, kind, capacity, bounds=None):
        self.kind = kind
        self.points = collections.deque(maxlen=capacity)
        self.bounds = bounds


class TimeSeriesStore(Logger):
    """Sample registered ServingMetrics sources into bounded rings on
    a cadence; see the module docstring.  ``capacity`` bounds every
    series (default 600 points ≈ 10 min at 1 Hz); ``sample_once()`` is
    the public synchronous tick (tests, the SLO monitor's
    determinism); ``start()`` runs it every ``interval_s`` on a
    daemon thread."""

    #: lock-discipline map (ISSUE 15): the rings and wiring lists are
    #: read by endpoint snapshots and the SLO monitor while the
    #: sampler thread folds — all under ``_lock``.  The error counters
    #: (probe_errors, listener_errors) stay unguarded: they are
    #: touched only on the sampling thread (or the test driving
    #: ``sample_once()`` in its place).
    _guarded_by = {
        "_sources": "_lock", "_probes": "_lock",
        "_listeners": "_lock", "_series": "_lock",
        "samples": "_lock", "last_sample_wall_s": "_lock",
    }

    def __init__(self, interval_s=1.0, capacity=600, name="telemetry"):
        self.name = name
        self.interval_s = float(interval_s)
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.capacity = int(capacity)
        if self.capacity < 2:
            raise ValueError("capacity must be >= 2 (rates need two "
                             "points)")
        self._lock = lockcheck.make_lock("timeseries._lock")
        self._sources = []               # (key, ServingMetrics)
        self._probes = []
        self._listeners = []
        self._series = {}                # name -> _Series
        self.samples = 0
        #: separate failure counters: a flaky probe at startup must
        #: never use up the LISTENER path's log budget (a dead SLO
        #: monitor with no log line would be an invisible outage)
        self.probe_errors = 0
        self.listener_errors = 0
        self.last_sample_wall_s = 0.0
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- wiring
    def add_source(self, metrics, key=None):
        """Sample ``metrics`` (a ServingMetrics) each tick under
        ``key`` (default: its registry identity — name plus instance
        labels, so replicas keep distinct rows)."""
        if key is None:
            from veles_tpu.serving.metrics import _registry_key
            key = _registry_key(metrics)
        with self._lock:
            self._sources.append((str(key), metrics))
        return self

    def add_probe(self, fn):
        """Run ``fn()`` at the top of every tick (BEFORE sources are
        sampled) — the runtime-gauge writers.  A probe that raises is
        counted (``probe_errors``) and logged once per storm, never
        fatal: telemetry must not take serving down."""
        with self._lock:
            self._probes.append(fn)
        return self

    def add_listener(self, fn):
        """Run ``fn()`` AFTER every completed tick — the SLO monitor
        rides here so objectives are evaluated once per sampling
        window over fresh points."""
        with self._lock:
            self._listeners.append(fn)
        return self

    # ------------------------------------------------------------ sampling
    def sample_once(self):
        """One synchronous tick: probes, then one snapshot per source
        folded into the rings, then listeners.  Returns the tick's
        ``sampled_at`` offset."""
        t = monotonic_offset()
        t0 = time.perf_counter()
        with self._lock:
            probes = list(self._probes)
            sources = list(self._sources)
        for fn in probes:
            try:
                fn()
            except Exception as e:   # noqa: BLE001 — never fatal
                self.probe_errors += 1
                if self.probe_errors <= 3 \
                        or self.probe_errors % 100 == 0:
                    # first few immediately, then a heartbeat — a
                    # permanent failure stays visible in the logs
                    # without flooding them
                    self.warning("telemetry probe failed (%d): %s",
                                 self.probe_errors, e)
        # the FLAT base snapshot, explicitly: RouterMetrics.snapshot()
        # embeds a full snapshot of every replica, which the fold
        # ignores — on a fleet the replicas are their own sources, so
        # building those embedded copies each tick would double the
        # sampling cost for nothing
        snaps = [(key, ServingMetrics.snapshot(m))
                 for key, m in sources]
        with self._lock:
            for key, snap in snaps:
                self._fold(key, snap, t)
            self.samples += 1
            self.last_sample_wall_s = time.perf_counter() - t0
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn()
            except Exception as e:   # noqa: BLE001 — never fatal
                self.listener_errors += 1
                if self.listener_errors <= 3 \
                        or self.listener_errors % 100 == 0:
                    self.warning("telemetry listener failed (%d): %s",
                                 self.listener_errors, e)
        return t

    def _ring(self, name, kind, bounds=None):
        # caller-holds: _lock
        s = self._series.get(name)
        if s is None:
            s = self._series[name] = _Series(kind, self.capacity,
                                             bounds)
        return s

    def _fold(self, key, snap, t):
        # caller-holds: _lock
        """One source snapshot into the rings (store lock held)."""
        for cname in ("requests", "responses", "rejected", "shed",
                      "errors", "dispatches", "rows"):
            self._ring("%s.counter.%s" % (key, cname),
                       "counter").points.append((t, snap[cname]))
        for cname, v in snap.get("counters", {}).items():
            self._ring("%s.counter.%s" % (key, cname),
                       "counter").points.append((t, v))
        for gname, v in snap.get("gauges", {}).items():
            if isinstance(v, (int, float)):
                self._ring("%s.gauge.%s" % (key, gname),
                           "gauge").points.append((t, v))
        for ename, v in snap.get("ewma", {}).items():
            self._ring("%s.ewma.%s" % (key, ename),
                       "gauge").points.append((t, v))
        for hname in ("queue_wait", "batch_size", "latency", "ttft",
                      "decode_step"):
            h = snap.get(hname)
            if not isinstance(h, dict) or "buckets" not in h:
                continue
            bounds = tuple(h["buckets"].keys())
            ring = self._ring("%s.hist.%s" % (key, hname), "hist",
                              bounds)
            sm = h["sum"]
            if not (isinstance(sm, (int, float))
                    and math.isfinite(sm)):
                # a hostile NaN observation poisons the cumulative sum
                # forever — keep the ring strict-JSON (counts/buckets
                # still work; only the sum-derived mean degrades)
                sm = 0.0
            ring.points.append(
                (t, (h["count"], sm, tuple(h["buckets"].values()))))

    # ------------------------------------------------------------- reading
    @staticmethod
    def _window_points(points, seconds, now):
        lo = now - seconds
        return [p for p in points if p[0] >= lo]

    def window(self, name, seconds):
        """Windowed read of one series over the last ``seconds``:
        counters → restart-tolerant delta + rate, gauges → last/min/
        max/mean, histograms → delta count/sum/mean + bucket-resolved
        p50/p95.  Returns None for an unknown series or a window with
        fewer than one point (counters/hists need two for a delta —
        they report zero-delta until then)."""
        now = monotonic_offset()
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            pts = self._window_points(s.points, seconds, now)
            kind, bounds = s.kind, s.bounds
        return self._window_stats(kind, bounds, pts)

    @classmethod
    def _window_stats(cls, kind, bounds, pts):
        """Windowed stats over an already-copied point list — ONE
        implementation for :meth:`window` and :meth:`snapshot`, so a
        snapshot's stats and its raw points always come from the SAME
        ring copy (no second lock round-trip, no torn payload)."""
        if not pts:
            return None
        span = pts[-1][0] - pts[0][0]
        if kind == "counter":
            delta = sum(max(0, b[1] - a[1])
                        for a, b in zip(pts, pts[1:]))
            return {"kind": "counter", "last": pts[-1][1],
                    "delta": delta, "span_s": round(span, 6),
                    "rate_per_s": round(delta / span, 6) if span > 0
                    else 0.0, "points": len(pts)}
        if kind == "gauge":
            vals = [p[1] for p in pts]
            return {"kind": "gauge", "last": _finite(vals[-1]),
                    "min": _finite(min(vals)),
                    "max": _finite(max(vals)),
                    "mean": _finite(sum(vals) / len(vals)),
                    "span_s": round(span, 6), "points": len(pts)}
        # histogram: delta between the window's edges (cumulative
        # counts are monotone per engine; a restart resets to a smaller
        # count — clamp like counters, pairwise)
        count = sum(max(0, b[1][0] - a[1][0])
                    for a, b in zip(pts, pts[1:]))
        total = sum(max(0.0, b[1][1] - a[1][1])
                    for a, b in zip(pts, pts[1:]))
        n_b = len(bounds)
        cum = [0] * n_b
        for a, b in zip(pts, pts[1:]):
            ca, cb = a[1][2], b[1][2]
            if len(ca) == n_b and len(cb) == n_b:
                for i in range(n_b):
                    cum[i] += max(0, cb[i] - ca[i])
        out = {"kind": "hist", "count_delta": count,
               "rate_per_s": round(count / span, 6) if span > 0
               else 0.0,
               "mean": round(total / count, 6) if count else 0.0,
               "bounds": list(bounds),
               "span_s": round(span, 6), "points": len(pts)}
        for q, label in ((0.5, "p50"), (0.95, "p95")):
            out[label] = cls._bucket_quantile(bounds, cum, count, q)
        return out

    @staticmethod
    def _bucket_quantile(bounds, cum_delta, count, q):
        """The smallest bucket bound whose cumulative delta covers
        quantile ``q`` — an upper estimate at bucket resolution.  The
        +Inf bucket reports the largest finite bound (documented as
        ">= last bound" — and keeps the payload strict-JSON: note
        ``float("+Inf")`` PARSES, so the overflow bucket must be
        detected by finiteness, not by ValueError); no events →
        0.0."""
        if not count:
            return 0.0
        want = q * count
        last_finite = 0.0
        for bound, c in zip(bounds, cum_delta):
            try:
                b = float(bound)
            except ValueError:
                b = None
            if b is not None and not math.isfinite(b):
                b = None            # the "+Inf" overflow bucket
            if b is not None:
                last_finite = b
            if c >= want:
                return b if b is not None else last_finite
        return last_finite

    def count_in_window(self, name, seconds, below_s):
        """Histogram helper for the SLO layer: (events ≤ ``below_s``,
        total events) over the window, resolved at bucket granularity
        — the good count is read at the LAST bound <= ``below_s`` (a
        threshold between bounds rounds DOWN), so bucket resolution
        can only over-alert, never hide a violation behind the next
        bound up; a threshold below every bound counts nothing as
        good."""
        now = monotonic_offset()
        with self._lock:
            s = self._series.get(name)
            if s is None or s.kind != "hist":
                return 0, 0
            pts = self._window_points(s.points, seconds, now)
            bounds = s.bounds
        if len(pts) < 2:
            return 0, 0
        n_b = len(bounds)
        cum = [0] * n_b
        count = 0
        for a, b in zip(pts, pts[1:]):
            count += max(0, b[1][0] - a[1][0])
            ca, cb = a[1][2], b[1][2]
            if len(ca) == n_b and len(cb) == n_b:
                for i in range(n_b):
                    cum[i] += max(0, cb[i] - ca[i])
        good = 0
        for bound, c in zip(bounds, cum):
            try:
                b = float(bound)    # NB "+Inf" PARSES to inf — the
            except ValueError:      # overflow bucket never qualifies
                b = math.inf        # as a finite threshold cut
            if math.isfinite(b) and b <= below_s:
                good = c            # the last bound under the cut
            else:
                break               # bounds ascend: done
        return good, count

    def counter_delta(self, name, seconds):
        """Counter helper for the SLO layer: the restart-tolerant
        delta over the window (0 for unknown series — an absent signal
        burns no budget)."""
        w = self.window(name, seconds)
        if w is None or w["kind"] != "counter":
            return 0
        return w["delta"]

    def series_names(self, prefix=None):
        with self._lock:
            names = sorted(self._series)
        if prefix:
            names = [n for n in names if n.startswith(prefix)]
        return names

    def sources(self):
        """The sampled source keys, registration order."""
        with self._lock:
            return [k for k, _ in self._sources]

    def snapshot(self, window_s=60.0, points=True):
        """The ``GET /timeseries.json`` payload: every series'
        windowed stats (plus, with ``points``, its raw points inside
        the window — counters/gauges as ``[t, v]``, histograms as
        ``[t, count, sum]``), strict-JSON, stamped with the shared
        monotonic ``sampled_at``."""
        now = monotonic_offset()
        window_s = float(window_s)
        with self._lock:
            # ONE consistent copy per series: the windowed stats and
            # the raw points below come from the same ring state (a
            # sampler tick landing mid-snapshot cannot tear them), and
            # the lock is taken once, not once per series
            rings = {n: (s.kind, s.bounds,
                         self._window_points(s.points, window_s, now))
                     for n, s in sorted(self._series.items())}
            samples = self.samples
        out = {"name": self.name,
               "sampled_at": round(now, 6),
               "interval_s": self.interval_s,
               "capacity": self.capacity,
               "window_s": window_s,
               "samples": samples,
               "series": {}}
        for n, (kind, bounds, pts) in rings.items():
            w = self._window_stats(kind, bounds, pts)
            if w is None:
                continue
            if points:
                if kind == "hist":
                    # cumulative bucket counts ride along so a
                    # captured export can recompute windowed
                    # percentiles/burns offline (tools/slo_report.py)
                    w["series"] = [[round(t, 6), c, round(sm, 9),
                                    list(cum)]
                                   for t, (c, sm, cum) in pts]
                else:
                    w["series"] = [[round(t, 6), _finite(v)]
                                   for t, v in pts]
            out["series"][n] = w
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="telemetry-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(10.0, 2 * self.interval_s))
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:   # noqa: BLE001 — sampler survives
                self.warning("telemetry sample failed: %s", e)


def telemetry_for(server, interval_s=1.0, capacity=600,
                  extra_sources=(), probes=True):
    """Build a :class:`TimeSeriesStore` wired over ``server`` — an
    :class:`LMEngine` or a :class:`Router` fleet: one source per
    replica's metrics (plus the router's own), one
    :func:`runtime_probe` per engine.  THE construction ``serve_lm``
    and the chaos/bench harnesses share, so what ships is what is
    measured."""
    store = TimeSeriesStore(interval_s=interval_s, capacity=capacity)
    engines = getattr(server, "replicas", None)
    if engines is None:
        engines = [server]
    else:
        store.add_source(server.metrics)
    for e in engines:
        store.add_source(e.metrics)
        if probes:
            store.add_probe(runtime_probe(e))
    for m in extra_sources:
        store.add_source(m)
    return store


# ------------------------------------------------------------ default store
_default = None   # guarded-by: _default_lock
_default_lock = threading.Lock()


def set_default(store):
    """Publish ``store`` as the process's default telemetry store —
    ``web_status.py`` serves it at ``/timeseries.json`` so the
    dashboard and the serving port expose the same rings."""
    global _default
    with _default_lock:
        _default = store
    return store


def get_default():
    with _default_lock:
        return _default
