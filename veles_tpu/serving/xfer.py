"""Explicit device↔host transfer shims + the transfer-guard witness
(ISSUE 17).

The serving hot path must never transfer data between host and device
IMPLICITLY: an unnoticed ``jnp.asarray(python_scalar)`` in a dispatch
argument, or a ``numpy.asarray`` / ``int()`` readback of a jit output,
is a synchronous round-trip the profiler attributes to nothing — the
host silently re-enters the compiled program's loop (the dataflow
thesis this repo reproduces forbids exactly that).  This module makes
every legitimate boundary EXPLICIT and makes everything else fail
loudly:

- :func:`to_device` — host value (python scalar / list / numpy array)
  → committed device array via ``jax.device_put``, the transfer JAX's
  ``transfer_guard`` classifies as explicit.  THE way a hot-path
  method builds a dispatch argument.
- :func:`to_host` — device array (or tree) → numpy via
  ``jax.device_get``, the explicit device→host read.  THE way a
  hot-path method reads a jit output.  Also the static host-sync
  pass's taint sink: a value routed through ``to_host`` is host data,
  so a following ``int()`` / ``numpy.asarray`` is not a finding.
- :func:`arm` / :func:`disarm` / :func:`guard` — the RUNTIME WITNESS
  (same discipline as ``lockcheck``'s lock-order witness): the serving
  test suites arm a ``jax.transfer_guard`` mode via
  ``tests/conftest.py``, and the engine worker loop (plus ``start()``
  warmup) enters ``with xfer.guard():`` — JAX's guard state is
  THREAD-LOCAL, so the context must be entered on the worker thread
  itself, which is exactly where the hot path runs.  Unarmed,
  ``guard()`` is a null context: zero overhead in production.

An implicit transfer under the armed guard raises a loud
``jax.errors.TransferGuardError`` (surfaced through the failing
request's future) with the offending stack — the runtime half of
``tools/veles_lint.py``'s static host-sync pass.
"""

from __future__ import annotations

import contextlib

import numpy

#: armed transfer-guard mode ("disallow" / "log") or None (unarmed);
#: written by arm()/disarm() from test setup BEFORE worker threads
#: start, read once per guard() entry — no lock needed
_mode = None


def arm(mode="disallow"):
    """Arm the transfer-guard witness: every ``guard()`` context
    entered after this (engine worker loops, warmup) enforces
    ``jax.transfer_guard(mode)``.  Call before ``LMEngine.start()`` so
    the worker thread picks it up."""
    global _mode
    if mode not in ("disallow", "log", "allow"):
        raise ValueError("transfer-guard mode must be disallow/log/"
                         "allow (got %r)" % (mode,))
    _mode = mode


def disarm():
    global _mode
    _mode = None


def armed():
    return _mode is not None


@contextlib.contextmanager
def _host_boundary_guard(mode):
    # host↔device ONLY: the blanket jax.transfer_guard also polices
    # device→device moves, but a replica jit pulling an uncommitted
    # arg onto its own device slice (router placement) is legitimate
    # dataflow, not a host sync — the witness guards the host edge.
    import jax
    with jax.transfer_guard_host_to_device(mode), \
         jax.transfer_guard_device_to_host(mode):
        yield


def guard():
    """The context a worker loop runs under: the host↔device
    transfer guards when armed, a null context otherwise (one
    module-global None-check — the lockcheck/faults discipline)."""
    if _mode is None:
        return contextlib.nullcontext()
    return _host_boundary_guard(_mode)


def boundary():
    """A DECLARED user-code transfer boundary: within it, host↔device
    transfers are allowed even under an armed witness.  The batcher
    wraps its ``forward`` call in this — forward is USER code (a
    jitted model in production, a plain host function in tests) whose
    internal transfer policy is the user's own; the witness polices
    the serving loop AROUND the boundary, not inside it.  Unarmed: a
    null context."""
    if _mode is None:
        return contextlib.nullcontext()
    return _host_boundary_guard("allow")


def to_device(x, dtype=None, device=None):
    """EXPLICIT host→device transfer: the one way hot-path code turns
    a host value (scalar, list, numpy array) into a dispatch argument.
    ``numpy.asarray`` first (host-side, free for arrays already of
    ``dtype``), then ``jax.device_put`` — explicit under any
    transfer-guard mode."""
    import jax
    return jax.device_put(numpy.asarray(x, dtype), device)


def to_host(x):
    """EXPLICIT device→host transfer: materialize a jit output (array
    or tree of arrays) as numpy via ``jax.device_get``.  Blocks until
    the device value is ready — the fence the host-sync pass's
    unfenced-timing rule credits."""
    import jax
    return jax.device_get(x)
