"""Serving subsystem — inference traffic at scale (ISSUE 1).

The inference-traffic counterpart of ``veles_tpu.parallel``: where the
direct REST path (``restful_api.py``) pays one device dispatch per HTTP
request, this package amortizes dispatch across concurrent clients.

- :mod:`veles_tpu.serving.batcher` — :class:`MicroBatcher`: dynamic
  micro-batching of ``/predict`` traffic into padded power-of-two batch
  buckets (warmed at start), with admission control (bounded queue →
  :class:`Overloaded` / HTTP 429 + ``Retry-After``) and per-request
  deadlines (:class:`DeadlineExceeded` / HTTP 503).
- :mod:`veles_tpu.serving.lm_engine` — :class:`LMEngine`: slot-based
  continuous batching for autoregressive LM decode over one shared KV
  cache (greedy path bit-identical to ``ops.transformer.generate``),
  plus the ISSUE 4 fast path: :class:`RadixPrefixCache` prompt-KV
  reuse, chunked prefill, and prompt-lookup speculative decoding.
  ``attn_kernel=`` (ISSUE 7) routes the paged engine's attention
  through the Pallas serving kernels in ``ops/pallas_kernels.py``
  (flash-decode over the page table + fused chunked-prefill with
  in-kernel row install) on TPU hardware, with an automatic XLA
  fallback metered as ``attn_kernel_dispatches`` /
  ``attn_kernel_fallbacks`` on ``/metrics``.
- :mod:`veles_tpu.serving.kv_pool` — :class:`KVPagePool`: the paged
  KV-cache allocator (ISSUE 6).  ``LMEngine(paged_kv=N)`` stores KV in
  fixed-size pages from one global pool behind per-lane page tables;
  prefix-cache hits become zero-copy page references (ref-counts +
  copy-on-write), and slot count is bounded by the pool, not by
  ``slots × max_len``.
- :mod:`veles_tpu.serving.router` — :class:`Router` (ISSUE 8): N
  data-parallel :class:`LMEngine` replicas — each optionally
  tensor-parallel over its own device slice (``LMEngine(tp=)``, mesh
  from ``parallel.make_tp_mesh``, weights by
  ``ops.transformer.lm_param_specs``) — placed by live metrics
  signals (queue depth, resident KV pages, TTFT/decode-step EWMAs),
  with hot-unregister draining that requeues a sick replica's pending
  requests.  ``serve_lm(tp=, replicas=)``, CLI ``--serve-tp`` /
  ``--serve-replicas`` / ``--serve-router``.
- :mod:`veles_tpu.serving.faults` — :class:`FaultPlan` (ISSUE 10):
  deterministic, seedable fault injection at named sites compiled into
  the engine/batcher/router/HTTP layers (dispatch errors, latency
  spikes, freezes, admission storms, transient HTTP errors) — each
  site a no-op when unarmed.  Drives the resilience layer:
  :class:`HealthChecker` (auto-quarantine via the router's drain path
  + half-open circuit breaker), ``Router(retries=, hedge_after_s=)``
  (re-place faulted requests on another replica with backoff; hedge
  tail-latency stragglers, first-complete wins), and
  ``LMEngine.checkpoint()/restore()`` (crash-safe re-admission of
  journaled work with allocator invariants re-verified).  CLI
  ``--serve-health`` / ``--serve-hedge`` / ``--serve-retries`` /
  ``--fault-plan``; harness ``tools/chaos_bench.py`` /
  ``tools/chaos_smoke.py``.
- :mod:`veles_tpu.serving.model_manager` — :class:`ModelManager`
  (ISSUE 11): the publisher loop closing trainer→serving — watches a
  snapshot directory (the snapshotter's atomic output), validates and
  loads new checkpoints off the hot path, and drives zero-downtime
  weight updates: ``LMEngine.swap_weights()`` hot-installs a
  checkpoint into a live engine (in-flight lanes finish on the old
  weights or drain-and-requeue; structural mismatch refuses loudly),
  ``Router.deploy()`` rolls it out canary-first with a parity probe,
  live-signal watch and automatic rollback, and every reply is
  stamped with the ``weights_version`` that served it.
  ``serve_lm(model_dir=, canary=, auto_rollback=)``, CLI
  ``--serve-model-dir`` / ``--serve-canary`` /
  ``--serve-publish-interval``.
- :mod:`veles_tpu.serving.tracing` — :class:`SpanTracer` (ISSUE
  12): end-to-end request tracing — an ``http.request`` root span, one
  span per router placement attempt, queue wait, every prefill chunk /
  decode tick / speculative verify / COW copy, device dispatches fenced
  via ``block_until_ready`` only when armed.  Finished requests land in
  a bounded flight-recorder ring (errored/deadline-blown requests
  auto-dump a waterfall), export as Chrome-trace/Perfetto JSON (``GET
  /trace.json?last=N``), and aggregate into the per-op cost ledger
  (``tools/trace_report.py``).  ``serve_lm(trace=)``, CLI
  ``--serve-trace off|errors|sample:P|all``; unarmed cost is one
  attribute-is-None check per site (the ``faults.py`` discipline).
- :mod:`veles_tpu.serving.metrics` — :class:`ServingMetrics`:
  lock-cheap counters/histograms (queue wait, batch size, latency
  percentiles, shed/429, slot occupancy) with a snapshot API and a
  Prometheus renderer (served by ``web_status.py`` at ``/metrics``).
- :mod:`veles_tpu.serving.timeseries` — :class:`TimeSeriesStore`
  (ISSUE 14): continuous telemetry — every metrics family sampled on
  a background cadence into bounded rings (counters → windowed rates,
  gauges → min/max/mean, histogram deltas → windowed p50/p95), plus
  runtime/device gauges (live jit ``compile_programs``, process RSS,
  ``jax`` device memory, live MFU from the lm_bench FLOPs model,
  megastep waste fraction) written by :func:`runtime_probe` each
  tick.  ``GET /timeseries.json?window=S``; the serving hot path has
  zero telemetry sites (pull model).  The tracer additionally keeps
  the per-op cost ledger INCREMENTALLY (``SpanTracer.live_ledger``,
  ``GET /ledger.json``) — same dedup-by-dispatch-id rows as
  ``tools/trace_report.py``, no export round-trip.
- :mod:`veles_tpu.serving.lockcheck` — :class:`LockOrderWitness`
  (ISSUE 15): the runtime half of the concurrency-analysis layer.
  Serving locks are built through :func:`lockcheck.make_lock` /
  :func:`lockcheck.make_condition` (one module-global None-check per
  operation when unarmed); an armed witness records the per-thread
  lock-acquisition graph, flags ordering cycles (potential deadlocks)
  and locks held across device dispatches, with both stacks as
  evidence.  Armed around the serving test suites by
  ``tests/conftest.py``; the static half — which attribute needs
  which lock, traced-purity of jitted bodies — is
  ``tools/veles_lint.py`` (rides tier-1 as ``tests/test_lint.py``).
- :mod:`veles_tpu.serving.slo` — :class:`SLOMonitor` (ISSUE 14):
  declarative objectives (availability, TTFT/decode-step latency,
  shed rate) evaluated as multi-window error-budget BURN RATES over
  the store, ok→warn→page state machine per (source, objective)
  (``slo_state`` gauges, ``slo_pages_total``), ``GET /slo.json``, and
  a router hook: a page-level burn on one replica feeds the PR 10
  :class:`HealthChecker` (``note_slo_page``) as a first-class health
  signal.  ``serve_lm(telemetry=, slo=)``, CLI ``--serve-telemetry``
  / ``--serve-slo FILE``; human panel at ``GET /status``.

The engines are OPTIONAL: ``restful_api.py`` keeps the direct
one-dispatch-per-request path for single-user/debug use and routes
through here when asked (``RESTfulAPI.enable_batching``, ``serve_lm``'s
``slots=``, CLI ``--serve-batch`` / ``--serve-slots``).
"""

from veles_tpu.serving.batcher import (DeadlineExceeded, MicroBatcher,
                                       Overloaded, PoolExhausted,
                                       batch_buckets)
from veles_tpu.serving.faults import (FaultPlan, InjectedFault,
                                      InjectedHTTPError)
from veles_tpu.serving.kv_pool import KVPagePool
from veles_tpu.serving.lockcheck import (LockOrderViolation,
                                         LockOrderWitness)
from veles_tpu.serving.lm_engine import (LMEngine, RadixPrefixCache,
                                         prompt_bucket, propose_draft)
from veles_tpu.serving.metrics import (ServingMetrics, get,
                                       render_prometheus)
from veles_tpu.serving.model_manager import (ModelManager,
                                             load_lm_params,
                                             validate_lm_params)
from veles_tpu.serving.router import (HealthChecker, NoLiveReplicas,
                                      Router, RouterMetrics,
                                      replica_device_slices)
from veles_tpu.serving.slo import Objective, SLOMonitor
from veles_tpu.serving.timeseries import (TimeSeriesStore,
                                          decode_flops_per_token,
                                          peak_flops_estimate,
                                          runtime_probe,
                                          telemetry_for)
from veles_tpu.serving.tracing import (SpanTracer, TraceContext,
                                       cost_ledger, format_waterfall,
                                       verify_integrity)

__all__ = ["MicroBatcher", "LMEngine", "RadixPrefixCache",
           "SpanTracer", "TraceContext", "cost_ledger",
           "format_waterfall", "verify_integrity",
           "TimeSeriesStore", "SLOMonitor", "Objective",
           "telemetry_for", "runtime_probe",
           "decode_flops_per_token", "peak_flops_estimate",
           "KVPagePool", "LockOrderViolation", "LockOrderWitness",
           "Router", "RouterMetrics", "HealthChecker",
           "ModelManager", "ServingMetrics", "FaultPlan",
           "InjectedFault",
           "InjectedHTTPError", "NoLiveReplicas", "Overloaded",
           "DeadlineExceeded",
           "PoolExhausted", "batch_buckets", "prompt_bucket",
           "propose_draft", "get", "load_lm_params",
           "render_prometheus",
           "replica_device_slices", "validate_lm_params"]
