"""Continuous LM decode — slot-based batching over one shared KV cache.

The LM-traffic half of the serving subsystem (ISSUE 1).  ``serve_lm``'s
direct path decodes one prompt at a time: a second client waits for the
whole first decode even though the decode step is embarrassingly
batchable.  :class:`LMEngine` keeps a fixed pool of ``slots`` decode
lanes sharing one batched KV cache (per block: (slots, kv_heads,
max_len, head_dim)) and runs ONE vmapped decode step per token across
every active lane — vLLM-style continuous batching on a jit substrate:

- an arriving prompt is PREFILLED into any free slot mid-flight
  (``ops/transformer.py::prefill`` at a power-of-two prompt bucket,
  installed into the big cache at the slot index);
- every engine tick advances ALL active slots by one token via a single
  jitted vmap of ``ops/transformer.py::block_decode_step`` (per-slot
  positions — each lane is at its own depth in its own sequence);
- a finished sequence frees its slot immediately and the next queued
  prompt takes it, so decode throughput scales with slot count instead
  of serializing per prompt.

The SERVING FAST PATH (ISSUE 4) adds three independently-toggled
optimizations, each preserving the greedy contract below:

- ``prefix_cache=N`` — a chunk-granular RADIX PREFIX CACHE
  (:class:`RadixPrefixCache`) over prompt tokens: prompts sharing a
  prefix (system prompts, few-shot headers) reuse the already-computed
  KV rows for their shared full chunks instead of re-running prefill
  FLOPs.  Entries are ref-counted while a lane uses their trie path and
  LRU-evicted at capacity ``N`` chunks; rows are COPIED into the lane's
  shared-cache rows on install, so a later eviction (or poisoning
  attempt) can never corrupt an in-flight decode — correctness never
  depends on cache state, only speed does.
- ``prefill_chunk=C`` — CHUNKED PREFILL: the prompt runs as
  ceil(len/C) fixed-width chunk dispatches
  (``ops/transformer.py::chunk_apply``) interleaved with decode steps,
  so one long prompt neither head-of-line-blocks the decode lanes nor
  forks a compile per prompt-length bucket (ONE chunk program total).
- ``spec_k=K`` — PROMPT-LOOKUP SPECULATIVE DECODING: an n-gram match
  against the lane's own prompt+output proposes K draft tokens (no
  draft model), verified in ONE batched chunk dispatch; every accepted
  token is by construction exactly the greedy token (acceptance
  compares the draft against the verifier's own argmax), so accepted
  runs yield multiple tokens per dispatch — sub-1 dispatches/token on
  repetitive or structured text — while a full miss still yields the
  one greedy token a plain step would have.

The PAGED KV CACHE (ISSUE 6, ``paged_kv=N``) replaces the contiguous
per-slot KV region with fixed-size PAGES (page = ``prefill_chunk``
tokens) drawn from one global pool per block, indexed through a
per-lane page table (``ops/attention.py::paged_view``/``paged_write``;
allocator in ``serving/kv_pool.py``):

- a lane RESERVES only the pages its own ``len(prompt) + n_new +
  spec_k`` span needs, so slot count is bounded by the POOL, not by
  ``slots × max_len`` — lanes of wildly different lengths share one
  region and the mixed-length bench fits ≥2× the lanes in the same KV
  bytes;
- prefix-cache hits become page REFERENCES: the trie stores page ids,
  a hit bumps a ref-count and writes the id into the lane's table —
  zero device copies, zero dispatches (the contiguous path's row-copy
  install is metered as ``kv_row_copies`` for contrast, and stays);
- appends into a SHARED page copy-on-write first (one page-copy
  dispatch; the other referents keep bit-identical rows) — structurally
  rare, because shared pages are exactly full prompt chunks and lanes
  append past their prompt;
- a request whose reservation cannot be met QUEUES (its page demand is
  re-tried every tick, after pressing the prefix cache to drop
  unpinned entries) and sheds 503 at its deadline; a backlog already
  covering the whole pool rejects new arrivals with
  ``PoolExhausted`` (HTTP 429) — pool pressure never wedges a lane.

The SERVING ATTENTION KERNELS (ISSUE 7, ``attn_kernel=``) swap the
paged programs' attention core for the Pallas suite in
``ops/pallas_kernels.py``: the decode/verify dispatches run
:func:`~veles_tpu.ops.pallas_kernels.paged_flash_decode` (the page
table walked INSIDE the kernel — no ``paged_view`` gather ever
materializes a lane's dense cache view) and the chunk program runs
:func:`~veles_tpu.ops.pallas_kernels.paged_flash_prefill` (chunk K/V
attended from VMEM and installed into the pool in the kernel
epilogue).  Routing resolves ONCE at construction: 'auto' (or True)
uses the kernels on real TPU hardware and falls back to the XLA path
everywhere else (off-TPU, contiguous KV layout, unsupported geometry
— logged once, metered per dispatch as ``attn_kernel_fallbacks`` vs
``attn_kernel_dispatches``); 'force' insists even off-TPU (interpret
mode — the parity tests' end-to-end gear, far too slow for traffic).
Decode/verify additionally slice the page table to the LIVE width
ladder (``_live_width``): a step pays for the pages the batch actually
occupies, one program per power-of-two ladder entry.

SHARDED SERVING (ISSUE 8, ``tp=N``) runs every program above under a
one-axis ``('tp',)`` mesh: weights are head-/column-sharded by
``ops/transformer.py::lm_param_specs`` (megatron split — wq/wk/wv by
head group, wo/w2 by row so GSPMD inserts one all-reduce per block),
the KV pool/caches shard over their kv_heads axis, and the page
tables, host allocator and every program stay EXACTLY as above — the
head shard and the page indirection compose because neither is a
shape.  Output shardings are pinned to the input layout so the mesh
adds zero programs (the jit-guard bound holds per replica).  The
Pallas kernels are single-device programs, so a TP engine serves
through the XLA path (metered as ``attn_kernel_fallbacks`` when
kernels were requested).  ``devices=`` narrows the engine to a device
slice — N independent engine REPLICAS (each optionally TP-sharded
over a disjoint slice) stack behind ``serving/router.py`` for the
data-parallel axis.

ZERO-DOWNTIME WEIGHT UPDATES (ISSUE 11, :meth:`LMEngine.swap_weights`)
hot-install a new checkpoint into a LIVE engine: the new tree is
validated structurally (shape/dtype/treedef — a mismatch refuses
loudly and the old weights keep serving), ``device_put`` under the
engine's existing placement (the tp mesh re-shards shard-by-shard via
``lm_param_specs``; same shapes → the already-compiled programs serve
the new weights, zero recompiles), and applied by the worker at a tick
boundary.  In-flight lanes either FINISH on the old weights (the
default: admission holds, the old tree stays pinned until its last
lane completes, then one pointer assignment swaps) or — ``drain=True``
— are withdrawn whole and re-queued at the head, re-decoding from
scratch on the new weights with their futures resolving exactly once
(the engine-internal analogue of the router's drain re-placement).
Every result is stamped with the ``weights_version`` that produced it,
so mixed-fleet replies are attributable during a rolling deploy
(``serving/router.py::Router.deploy``).

The DECODE MEGASTEP (ISSUE 13, ``megastep=K``) fuses K decode
iterations into ONE jitted ``lax.scan`` program, so the host pays one
dispatch (and one lock-guarded tick of admission/tracing/bookkeeping)
per K generated tokens instead of per token — the whole-loop-on-device
move PR 2's ``window_scan_fn`` made for training epochs, applied to
the serving inner loop:

- the scan body is exactly today's batched step (or, with ``spec_k``,
  a propose → verify → accept leg whose n-gram draft proposal runs
  IN-GRAPH over a carried token-history buffer —
  ``ops/transformer.py::propose_draft_in_graph`` — so speculation
  composes with the megastep instead of forcing a host round-trip per
  draft);
- greedy argmax selection, ``paged_write`` KV appends through the
  traced page tables, and per-lane position/frontier advance all stay
  inside the program;
- a lane that exhausts its ``n_new`` mid-program is MASKED, not
  returned: its carry freezes (position/last token stop advancing),
  its emitted slots read -1, and — paged — its K/V writes are
  redirected to the scratch page (``paged_write(write_mask=)``), so a
  dead iteration can never touch an allocated page.  The wasted
  iterations are metered (``megastep_wasted_iterations``) so the K
  tradeoff is measured, not guessed;
- the HOST operates at MEGASTEP BOUNDARIES: admission, deadline
  shedding (one queue sweep per boundary — ``_boundary_shed``),
  completion detection (the per-lane emitted-token buffers are scanned
  for each lane's exact ``n_new``), swap application
  (``_maybe_apply_swap``) and fault sites all run once per megastep,
  and tracing records ONE ``decode.megastep`` span per dispatch
  (carrying K and each lane's tokens emitted) so the ISSUE 12 cost
  ledger counts the fused program once, never the folded per-token
  work.

``megastep=1`` (and 0, the default) keeps today's per-tick path
bit-for-bit; any K is bit-identical to it anyway (the scan body IS the
step program), which the parity matrix pins across the full
{paged_kv, prefix_cache, prefill_chunk, spec_k, attn_kernel, tp}
feature set.  With the Pallas ``paged_flash_decode`` kernel active the
whole K-step loop never leaves the device.

Decoding is GREEDY (temperature 0) — bit-identical to
``ops/transformer.py::generate`` for the same prompt WHATEVER fast-path
combination is enabled, which is the serving contract (sampled
requests fall back to the direct path upstream; the Pallas kernels'
online softmax matches the XLA softmax to fp32 roundoff, preserving
every greedy argmax the parity matrix pins).  Compile count is
bounded: one step program, one prefill program per prompt bucket, one
install program, plus (fast path) one chunk-prefill program, one
chunk-install/extract pair, and one verify program per (engine) ``k``;
paged mode compiles one chunk, one step, one verify and one page-copy
program TOTAL (the page-table indirection is traced data, never a
shape).  The megastep adds ONE fused program per (live-width ladder
entry × K) family — K is fixed per engine, so that is one program
contiguous / one per ladder entry paged, the jit-guard-asserted bound.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving import lockcheck, tracing, xfer
from veles_tpu.serving.batcher import (DeadlineExceeded, Overloaded,
                                       PoolExhausted)
from veles_tpu.serving.kv_pool import KVPagePool
from veles_tpu.serving.metrics import ServingMetrics


class _Request:
    __slots__ = ("prompt", "true_len", "n_new", "future", "t_enq",
                 "deadline", "cancelled", "pages", "trace", "tspan",
                 "seed")

    def __init__(self, prompt, n_new, deadline_s, pages=0):
        self.prompt = prompt          # (s,) int32, unpadded
        self.true_len = len(prompt)
        self.n_new = n_new
        self.future = Future()
        self.future.request = self    # cancellation handle
        self.t_enq = time.monotonic()
        self.deadline = self.t_enq + deadline_s
        self.cancelled = False
        #: paged mode: worst-case page demand (admission reservation)
        self.pages = pages
        #: tracing (ISSUE 12): the request's TraceContext (or None) and
        #: its open queue-wait span handle — how the worker thread
        #: attributes its dispatch spans to the right request
        self.trace = None
        self.tspan = None
        #: seeded-sampling lane seed (ISSUE 19): the admission id —
        #: deterministic per submission order, so the same workload
        #: samples identically whatever engine configuration serves it
        self.seed = 0


class _Slot:
    """Host-side lane state; device state lives in the shared caches."""

    __slots__ = ("request", "emitted", "remaining", "pending", "pinned",
                 "cursor", "pages")

    def __init__(self, request):
        self.request = request
        self.emitted = []
        self.remaining = request.n_new
        #: chunked prefill still to run: [(tokens (C,), start, is_tail)]
        self.pending = []
        #: prefix-cache nodes pinned by this lane (released at finish)
        self.pinned = []
        #: trie node of the last matched/inserted chunk (None once the
        #: cache refused an insert — stop extending this lane's path)
        self.cursor = None
        #: paged mode: page ids backing this lane's table row, in
        #: lane-local order (owned AND referenced; released at finish)
        self.pages = []


class _Standby:
    """One standby-ring entry (ISSUE 19): a host-prefilled lane parked
    OUTSIDE the slot array, waiting to be published into the while-loop
    megastep's carry so a finishing slot can be re-armed in-graph.  It
    owns its pages (reserved and pinned like a live lane's) and its
    request's first token is already delivered — the entry is admitted
    work, never deadline-shed."""

    __slots__ = ("lane", "table", "pos", "last", "ready")

    def __init__(self, lane, table):
        self.lane = lane
        #: (max_pages,) int32 page-table row backing this entry
        self.table = table
        #: decode frontier after the tail prefill chunk
        self.pos = 0
        self.last = 0
        #: tail chunk done — publishable into the megastep carry
        self.ready = False


def prompt_bucket(true_len, max_len, floor=16):
    """Power-of-two prompt pad width (compile-count bound), capped at
    the cache length."""
    bucket = floor
    while bucket < true_len:
        bucket *= 2
    return min(bucket, max_len)


def propose_draft(history, k, max_ngram=3):
    """Prompt-lookup draft (arXiv:2304.04487 / prompt-lookup decoding):
    find the most recent earlier occurrence of the sequence's final
    n-gram (n = ``max_ngram`` down to 1) and propose the (up to ``k``)
    tokens that followed it.  Returns (m,) int32 with 1 <= m <= k —
    exactly the continuation that was found, unpadded, so callers can
    meter real draft tokens — or None when no n-gram recurs.

    Draft quality only affects SPEED: the verifier accepts a draft
    token only when it equals the verifier's own greedy argmax, so even
    an adversarial draft cannot change output."""
    history = numpy.asarray(history, numpy.int32).reshape(-1)
    n = len(history)
    for g in range(min(max_ngram, n - 1), 0, -1):
        # candidate windows must END strictly before the final position
        # (the tail itself is not a match for itself)
        if n - 1 < g:
            continue
        tail = history[n - g:]
        windows = numpy.lib.stride_tricks.sliding_window_view(
            history[:n - 1], g)
        hits = numpy.flatnonzero((windows == tail).all(axis=1))
        if not len(hits):
            continue
        s = int(hits[-1])               # most recent occurrence
        cont = history[s + g:s + g + k]
        if len(cont):
            return numpy.asarray(cont, numpy.int32)
    return None


class _PrefixNode:
    __slots__ = ("key", "rows", "children", "refs", "last_use", "parent")

    def __init__(self, key, rows, parent):
        self.key = key                # tuple of the chunk's tokens
        self.rows = rows              # per-block [(k, v)] (1, H, C, D)
        self.children = {}
        self.refs = 0
        self.last_use = 0
        self.parent = parent


class RadixPrefixCache:
    """Radix trie over prompt tokens at CHUNK granularity.

    A node holds the per-block KV rows of exactly ``chunk`` tokens whose
    absolute positions are [depth·chunk, (depth+1)·chunk) — valid for
    ANY prompt sharing that token prefix, because causal attention makes
    a position's K/V depend only on the tokens at and before it.  Keys
    are the chunk's literal tokens, so two prompts diverging mid-chunk
    hash to different keys and can never cross-contaminate (the
    poisoning case the parity suite pins).

    Entries are PINNED (ref-counted) while a lane's admission walk or
    insert path uses them and LRU-evicted leaf-first at ``capacity``
    chunks.  Lookup/insert/evict all run on the single engine worker
    thread — no locking.

    ``rows`` is opaque to the trie: the contiguous engine stores device
    ROW COPIES, the paged engine stores a PAGE ID (zero-copy sharing).
    ``on_evict(rows)`` fires whenever an entry is dropped — the paged
    engine releases the page's pool reference there, so trie eviction
    IS the pool's reclamation path under pressure (and pinned entries
    refusing eviction is what keeps lane-held pages safe).
    """

    def __init__(self, capacity, chunk, on_evict=None):
        if capacity < 1:
            raise ValueError("prefix cache capacity must be >= 1")
        self.capacity = int(capacity)
        self.chunk = int(chunk)
        self.on_evict = on_evict
        self.root = _PrefixNode(None, None, None)
        self.size = 0
        self._tick = 0

    def match(self, keys):
        """Longest cached prefix along ``keys`` (chunk-token tuples);
        returns the matched nodes in order, each pinned — pass them to
        :meth:`release` when the lane finishes."""
        self._tick += 1
        node, out = self.root, []
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            child.refs += 1
            child.last_use = self._tick
            out.append(child)
            node = child
        return out

    def insert(self, parent, key, rows):
        """Add one computed chunk under ``parent`` (root or the lane's
        previous node); returns the PINNED node — existing nodes are
        reused (first writer wins; identical content by construction) —
        or None when every entry is pinned and nothing can be evicted."""
        self._tick += 1
        node = parent.children.get(key)
        if node is None:
            while self.size >= self.capacity:
                if not self._evict_one():
                    return None
            node = _PrefixNode(key, rows, parent)
            parent.children[key] = node
            self.size += 1
        node.refs += 1
        node.last_use = self._tick
        return node

    def lookup_child(self, parent, key):
        """The one-chunk extension of ``parent`` by ``key``, PINNED, or
        None.  Called per pending chunk right before computing it: a
        sibling lane prefilling the same prompt may have inserted the
        chunk since this lane was admitted, and late hits are what make
        CONCURRENT shared-prefix arrivals converge on one prefill
        instead of all missing the cache they are about to fill."""
        node = parent.children.get(key)
        if node is None:
            return None
        self._tick += 1
        node.refs += 1
        node.last_use = self._tick
        return node

    def release(self, nodes):
        for node in nodes:
            node.refs -= 1

    def evict_one(self):
        """Drop the LRU unpinned leaf NOW (pool-pressure reclamation:
        the paged engine calls this until its page reservation fits or
        nothing more can go).  Returns True when an entry was dropped."""
        return self._evict_one()

    def evictable(self):
        """Upper bound on entries pool-pressure eviction can reclaim:
        the UNPINNED count (an unpinned interior node above a pinned
        child is counted but unreachable — close enough, since lanes
        pin whole root-anchored paths).  The paged engine checks this
        BEFORE evicting, so a hopeless reservation cannot flush the
        whole cache for nothing."""
        count, stack = 0, [self.root]
        while stack:
            for child in stack.pop().children.values():
                if child.refs == 0:
                    count += 1
                stack.append(child)
        return count

    def live_pins(self):
        """Total outstanding pin count across the trie — 0 whenever no
        lane is active (ISSUE 10: the orphan-pin leak check after
        faulted requests; a nonzero value at idle means a fault path
        forgot to release its admission walk)."""
        total, stack = 0, [self.root]
        while stack:
            for child in stack.pop().children.values():
                total += child.refs
                stack.append(child)
        return total

    def _evict_one(self):
        """Evict the least-recently-used unpinned LEAF (interior nodes
        keep their children's prefix reachable; they become leaves —
        and evictable — once their subtree ages out)."""
        best = None
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node.refs == 0 and (best is None
                                     or node.last_use < best.last_use):
                best = node
        if best is None:
            return False
        del best.parent.children[best.key]
        self.size -= 1
        if self.on_evict is not None:
            self.on_evict(best.rows)
        return True


class LMEngine(Logger):
    """Slot-based continuous batching over ``params`` (a portable
    transformer param tree, see ``TransformerTrainer._to_portable``).

    One worker thread owns the device state; clients :meth:`submit`
    single prompts (or :meth:`generate` a batch) and block on futures.
    ``max_len`` pins the shared cache length: every request must satisfy
    ``len(prompt) + n_new <= max_len`` (+ ``spec_k`` of speculation
    headroom when ``spec_k > 0`` — a verify dispatch writes up to k
    positions past the committed front).

    Fast-path knobs (ISSUE 4, all default-off; see the module
    docstring): ``prefill_chunk=C`` chunked prefill, ``prefix_cache=N``
    radix KV reuse over N cached chunks (implies chunking; default
    chunk 32), ``spec_k=K`` prompt-lookup speculative decoding with
    ``spec_ngram`` match length.  ``queue_tokens=T`` budgets ADMISSION
    by queued prompt tokens (not just request count): a long-prompt
    flood 429s early instead of building an unbounded prefill backlog
    (the head request always admits, so a single oversized prompt can
    not wedge an empty queue).

    ``megastep=K`` (ISSUE 13) fuses K decode iterations — or K
    propose→verify→accept legs under ``spec_k`` — into ONE jitted
    ``lax.scan`` dispatch, moving all host bookkeeping (admission,
    deadline shedding, completion, swaps, tracing) to megastep
    boundaries; 0/1 keeps the per-tick path.  See the module
    docstring.
    """

    #: lock-discipline map (ISSUE 15, checked by tools/veles_lint.py):
    #: the CROSS-THREAD state — client admission vs the worker loop —
    #: lives under ``_cond``.  Everything else (_lanes, _free, _pos,
    #: _last, _caches, _kv_pools, _page_tables, _pool, _trie,
    #: _pool_blocked) is owned by the worker thread alone and is
    #: deliberately NOT guarded (checkpoint() documents the torn-read
    #: consequences for its best-effort pool section).
    _guarded_by = {
        "_queue": "_cond",
        "_queued_tokens": "_cond",
        "_queued_pages": "_cond",
        "_journal": "_cond",
        "_rid": "_cond",
        "_pending_swap": "_cond",
        "_stop": "_cond",
    }

    def __init__(self, params, n_heads, max_len, slots=4, rope=False,
                 window=None, sinks=0, queue_depth=64, deadline_s=30.0,
                 metrics=None, name="lm", prefill_chunk=0,
                 prefix_cache=0, spec_k=0, spec_ngram=3,
                 queue_tokens=0, paged_kv=0, attn_kernel=None,
                 tp=0, devices=None, faults=None, version=0,
                 tracer=None, megastep=0, megastep_mode=None,
                 refill_ring=0, temperature=0.0, top_k=0,
                 sample_seed=None):
        import jax
        import jax.numpy as jnp
        if slots < 1:
            raise ValueError("slots must be >= 1")
        self.name = name
        #: optional serving/faults.py FaultPlan — every engine.* site
        #: is one is-None check when unarmed (ISSUE 10)
        self._faults = faults
        #: optional serving/tracing.py SpanTracer (ISSUE 12) — same
        #: unarmed discipline: every site is one is-None check
        self._tracer = tracer
        self.params = params
        self.n_heads = int(n_heads)
        self.max_len = int(max_len)
        # ---- sharded serving (ISSUE 8): ``tp >= 2`` runs EVERY engine
        # program under a one-axis ('tp',) mesh — weights head-/column-
        # sharded by ops/transformer.py::lm_param_specs, KV storage
        # sharded over its kv_heads axis — with the decode/chunk/verify
        # math UNCHANGED (GSPMD inserts the per-block all-reduce).
        # ``devices`` narrows the engine to a device SLICE: a
        # data-parallel replica (serving/router.py) owns devices
        # [i*tp, (i+1)*tp) of the host; tp<2 with ``devices`` pins a
        # single-device replica there.  Output shardings are pinned to
        # the input layout in _build_jits, so the compile count stays
        # at one program per family (the jit-guard bound) under the
        # mesh too.
        self.tp = int(tp or 0)
        if self.tp < 0:
            raise ValueError("tp must be >= 0 (got %d)" % self.tp)
        devices = list(devices) if devices is not None else None
        self._mesh = None
        self._device = None
        self._kv_shard = None
        self._repl_shard = None
        if self.tp >= 2:
            from veles_tpu.parallel import make_tp_mesh
            if self.n_heads % self.tp:
                raise ValueError(
                    "tp=%d must divide n_heads %d (whole attention "
                    "heads shard)" % (self.tp, self.n_heads))
            self._mesh = make_tp_mesh(self.tp, devices)
        elif devices:
            self._device = devices[0]
        self.slots = int(slots)
        self.rope = bool(rope)
        self.window = window
        self.sinks = int(sinks)
        self.queue_depth = int(queue_depth)
        self.deadline_s = float(deadline_s)
        self.queue_tokens = int(queue_tokens)
        self._paged = bool(paged_kv)
        if (prefix_cache or self._paged) and not prefill_chunk:
            prefill_chunk = min(32, self.max_len)   # cache granularity
            if self._paged:
                # the page size must divide max_len (the bit-parity
                # condition below) — default to the largest divisor
                while self.max_len % prefill_chunk:
                    prefill_chunk -= 1
        self.prefill_chunk = int(prefill_chunk)
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        if self.prefill_chunk < 0 or self.prefill_chunk > self.max_len:
            raise ValueError("prefill_chunk %d out of range (max_len %d)"
                             % (self.prefill_chunk, self.max_len))
        if self.spec_k < 0 or self.spec_k + 1 >= self.max_len:
            raise ValueError("spec_k %d out of range (max_len %d)"
                             % (self.spec_k, self.max_len))
        if self.spec_k and self.prefill_chunk \
                and self.spec_k + 1 > self.prefill_chunk:
            # a prefilling lane parks its step position at the chunk
            # frontier; the next chunk overwrites the verify dispatch's
            # k+1 garbage writes only when they fit inside one chunk
            raise ValueError("spec_k + 1 (%d) must not exceed "
                             "prefill_chunk (%d)"
                             % (self.spec_k + 1, self.prefill_chunk))
        if self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        #: decode megastep (ISSUE 13): K >= 2 fuses K decode (or
        #: propose/verify) iterations into one lax.scan dispatch;
        #: 0/1 = the per-tick path, bit-identical and unchanged.
        #: ISSUE 19: megastep='while' (or megastep_mode='while') swaps
        #: the fixed-K scan for a lax.while_loop whose cond exits as
        #: soon as every live lane finished its n_new — K stays the
        #: HARD iteration cap, so termination stays provable and the
        #: program family stays one per live-width ladder entry.
        if megastep == "while":
            megastep, megastep_mode = 16, "while"
        if megastep_mode not in (None, "scan", "while"):
            raise ValueError("megastep_mode must be 'scan' or 'while' "
                             "(got %r)" % (megastep_mode,))
        self.megastep = int(megastep or 0)
        self.megastep_mode = megastep_mode or "scan"
        if self.megastep < 0:
            raise ValueError("megastep must be >= 0 (got %d)"
                             % self.megastep)
        if self.megastep_mode == "while" and self.megastep < 2:
            raise ValueError("megastep_mode='while' needs megastep >= 2 "
                             "(the iteration cap)")
        #: standby refill ring (ISSUE 19): host-prefilled lanes the
        #: while-loop re-arms finishing slots from, in-graph
        self.refill_ring = int(refill_ring or 0)
        if self.refill_ring < 0:
            raise ValueError("refill_ring must be >= 0 (got %d)"
                             % self.refill_ring)
        if self.refill_ring and not (self._paged and
                                     self.megastep_mode == "while"):
            raise ValueError("refill_ring needs paged_kv and "
                             "megastep_mode='while' (the ring is "
                             "published into the while-loop carry as "
                             "page-table rows)")
        #: in-graph seeded sampling (ISSUE 19): temperature > 0 samples
        #: with counter-based prng streams keyed by (lane seed,
        #: position); 0 keeps greedy argmax and byte-identical programs
        self.temperature = float(temperature or 0.0)
        self.top_k = int(top_k or 0)
        if self.temperature < 0 or self.top_k < 0:
            raise ValueError("temperature and top_k must be >= 0")
        self._sampling = self.temperature > 0
        if self._sampling and sample_seed is None:
            raise ValueError("temperature > 0 needs sample_seed — "
                             "seeded reproducibility is the contract")
        self.sample_seed = (None if sample_seed is None
                            else int(sample_seed))
        self._sample_key_host = None
        if self._sampling:
            from veles_tpu.prng import RandomGenerator
            # FIXED stream name: the key derivation folds the stream
            # name into the seed, and sampled outputs must depend on
            # sample_seed alone — never on what the engine (or its
            # replica twin on another host) happens to be called
            self._sample_key_host = numpy.asarray(RandomGenerator(
                "lm-sample", self.sample_seed).base_key())
        if self._paged and self.max_len % self.prefill_chunk:
            # the paged lane view must tile max_len exactly: a partial
            # tail page would either truncate placeable rows or attend
            # rows past max_len (the chunk program additionally relies
            # on page-aligned starts)
            raise ValueError(
                "paged_kv needs max_len (%d) divisible by the page size "
                "(prefill_chunk, %d)" % (self.max_len,
                                         self.prefill_chunk))
        self.metrics = metrics or ServingMetrics(name)
        self.metrics.set_gauge("slots_total", self.slots)
        self.metrics.set_gauge("slots_busy", 0)
        self.metrics.set_gauge("tp_devices", self.tp or 1)
        #: the checkpoint generation currently serving (ISSUE 11):
        #: swap_weights bumps it, every finished request is stamped
        #: with the version that produced its tokens
        self.weights_version = int(version)
        self.metrics.set_gauge("weights_version", self.weights_version)
        #: in-flight swap_weights request (worker applies at tick
        #: boundaries; None almost always)
        self._pending_swap = None

        embed = params["embed"]
        d_model = embed.shape[1]
        head_dim = d_model // self.n_heads
        kv_heads = params["blocks"][0]["attn"]["wk"].shape[1] // head_dim
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if kv_heads % self.tp:
                raise ValueError(
                    "tp=%d must divide kv_heads %d (the KV cache "
                    "shards head-wise)" % (self.tp, kv_heads))
            # the KV arrays below shard over their kv_heads axis so
            # paged_view / mha_paged_chunk_step (and the contiguous
            # decode) stay one-program-per-family — the page-table
            # indirection and the head shard compose, neither is a
            # shape
            self._kv_shard = NamedSharding(
                self._mesh, P(None, "tp", None, None))
            self._repl_shard = NamedSharding(self._mesh, P())
        self.params = self._place_params(self.params)
        # ---- serving attention kernels (ISSUE 7): resolve the routing
        # ONCE here — platform and geometry are fixed for the engine's
        # lifetime, so the fallback decision never flaps mid-traffic.
        # attn_kernel: None = follow set_attention_backend
        # ('flash_serve' => 'auto'); 0/False = off; True/'auto' = Pallas
        # kernels on real TPU, XLA fallback elsewhere; 'force' = Pallas
        # even off-TPU (interpret mode — parity tests, not production).
        if attn_kernel is None:
            from veles_tpu.ops.attention import serving_kernel_default
            attn_kernel = "auto" if serving_kernel_default() else 0
        if attn_kernel is True:
            attn_kernel = "auto"
        if attn_kernel not in (0, False, "auto", "force"):
            raise ValueError("attn_kernel must be one of 0/False, "
                             "'auto', 'force' (got %r)" % (attn_kernel,))
        self.attn_kernel = attn_kernel or 0
        self._kernel_active = False
        self._kernel_fallback_reason = None
        if self.attn_kernel:
            from veles_tpu.ops.pallas_kernels import (
                on_tpu, serving_kernels_supported)
            ok, reason = serving_kernels_supported(
                self._paged, self.n_heads, kv_heads, head_dim,
                self.prefill_chunk, tp=self.tp)
            if ok and (self.attn_kernel == "force" or on_tpu()):
                self._kernel_active = True
            else:
                self._kernel_fallback_reason = reason or (
                    "no TPU backend (interpret-mode kernels are test "
                    "gear; pass attn_kernel='force' to insist)")
                # logged ONCE, here — not per dispatch
                self.warning(
                    "attn_kernel requested but using the XLA path: %s",
                    self._kernel_fallback_reason)
        self.metrics.set_gauge("attn_kernel_active",
                               int(self._kernel_active))
        #: the cost ledger's backend axis (ISSUE 12): which attention /
        #: program path this engine's device spans actually ran
        self._backend = ("pallas" if self._kernel_active
                         else "xla-tp%d" % self.tp if self.tp >= 2
                         else "xla")
        self._caches = None
        self._kv_pools = None
        self._pool = None
        self._page_tables = None
        self._max_pages = 0
        self._width_ladder = []
        if self._paged:
            self._max_pages = self.max_len // self.prefill_chunk
            # decode/verify table-width ladder (ISSUE 7 satellite): a
            # step only needs pages up to the batch's live frontier,
            # not the full max_len span — the table is sliced to the
            # smallest power-of-two width covering every lane, so the
            # per-token gather (or kernel grid) scales with what's
            # actually resident.  Power-of-two steps bound the compile
            # count at one step/verify program per LADDER ENTRY (the
            # jit-guard's per-family bound), the same discipline as the
            # contiguous path's prompt buckets.
            self._width_ladder = []
            w = 1
            while w < self._max_pages:
                self._width_ladder.append(w)
                w *= 2
            self._width_ladder.append(self._max_pages)
            num_pages = (self.slots * self._max_pages
                         if paged_kv is True else int(paged_kv))
            if num_pages < 1:
                raise ValueError("paged_kv pool must hold >= 1 page")
            self._pool = KVPagePool(num_pages, self.prefill_chunk)
            pool_shape = (num_pages + 1, kv_heads, self.prefill_chunk,
                          head_dim)          # +1: the scratch page
            self._kv_pools = [
                (self._place_kv(jnp.zeros(pool_shape, embed.dtype)),
                 self._place_kv(jnp.zeros(pool_shape, embed.dtype)))
                for _ in params["blocks"]]
            self._page_tables = numpy.zeros(
                (self.slots, self._max_pages), numpy.int32)
            self.metrics.set_gauge("kv_pages_total", num_pages)
        else:
            cache_shape = (self.slots, kv_heads, self.max_len, head_dim)
            self._caches = [
                (self._place_kv(jnp.zeros(cache_shape, embed.dtype)),
                 self._place_kv(jnp.zeros(cache_shape, embed.dtype)))
                for _ in params["blocks"]]
        self._trie = (RadixPrefixCache(
            prefix_cache, self.prefill_chunk,
            on_evict=self._pool.release if self._paged else None)
            if prefix_cache else None)
        #: per-slot device-facing scalars, host-owned between ticks
        self._pos = numpy.zeros(self.slots, numpy.int32)
        self._last = numpy.zeros(self.slots, numpy.int32)
        self._lanes = [None] * self.slots
        self._free = list(range(self.slots))
        #: standby refill ring (ISSUE 19): _Standby entries prefilled
        #: between boundaries, published into the while-loop carry
        self._ring = []

        self._queue = collections.deque()
        self._queued_tokens = 0
        self._queued_pages = 0
        self._pool_blocked = False
        self._cond = lockcheck.make_condition("lm_engine._cond")
        self._thread = None
        self._stop = False
        #: admission journal (ISSUE 10): rid -> _Request for every
        #: request not yet resolved — checkpoint() snapshots it so a
        #: supervisor can re-admit in-flight work after a crash
        self._journal = {}
        self._rid = 0
        self._build_jits()
        if self._paged:
            self._update_pool_gauges()

    # ----------------------------------------------------------- placement
    def _fault(self, site):
        """Fault-injection hook (ISSUE 10): free when no plan is
        attached — one attribute-is-None check on the hot path.  The
        lock-order witness (ISSUE 15) piggybacks here: every dispatch-
        class site doubles as a lock-held-across-dispatch probe, one
        module-global None-check when unarmed."""
        if self._faults is not None:
            self._faults.fire(site)
        if lockcheck._witness is not None:
            lockcheck._witness.dispatch(site)

    # ------------------------------------------------------------- tracing
    def _tfence(self, state, traced=True):
        """Dispatch fencing (ISSUE 12): jit returns before the device
        finishes, so a traced span must block on the outputs to time
        device wall, not enqueue.  ONLY called when tracing is armed
        AND the dispatch serves at least one SAMPLED request
        (``traced``) — ``sample:P`` traffic pays the sync only on its
        sampled fraction, and the unarmed path never syncs."""
        if self._tracer is not None and traced:
            import jax
            if lockcheck._witness is not None:
                lockcheck._witness.dispatch("engine.fence")
            jax.block_until_ready(state)

    def _trace_admitted(self, req):
        """Close the request's queue-wait span at slot assignment."""
        if req.tspan is not None:
            req.trace.tracer.end(req.tspan, attrs={
                "wait_s": round(time.monotonic() - req.t_enq, 6)})
            req.tspan = None

    def _trace_queue_end(self, req, error):
        """Close the queue-wait span on a non-admission exit (shed,
        cancel) so the finished tree carries no unclosed spans."""
        if req.tspan is not None:
            req.trace.tracer.end(req.tspan, error=error)
            req.tspan = None

    def _place_params(self, params):
        """Place one param tree per the engine's layout: megatron
        specs over the tp mesh (``lm_param_specs`` — weights head-/
        column-sharded, shard-by-shard device_put), committed to the
        replica's device, or left as given (the single-device
        default).  THE one placement path — construction and
        :meth:`swap_weights` share it, so a hot-swapped tree lands in
        exactly the layout the compiled programs expect (same shapes +
        same shardings = zero recompiles)."""
        import jax
        if self._mesh is not None:
            from jax.sharding import NamedSharding
            from veles_tpu.ops.transformer import lm_param_specs
            return jax.tree.map(
                lambda a, s: jax.device_put(
                    a, NamedSharding(self._mesh, s)),
                params, lm_param_specs(params))
        if self._device is not None:
            return jax.device_put(params, self._device)
        # single-device default: an EXPLICIT one-time placement — host
        # numpy weights left in place would re-transfer implicitly on
        # every dispatch (and trip the armed transfer guard)
        return jax.device_put(params)

    def _place_kv(self, arr):
        """Place one KV array per the engine's layout: head-sharded
        over the tp mesh, committed to the replica's device, or left
        uncommitted (the single-device default)."""
        import jax
        if self._mesh is not None:
            return jax.device_put(arr, self._kv_shard)
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return arr

    def _jit(self, fn, out_shardings=None):
        """``jax.jit`` with the output layout PINNED under a tp mesh:
        without the pin, GSPMD's chosen output sharding compares
        unequal to the device_put input layout and the second call of
        every family silently compiles a twin program — the exact
        recompile ladder the jit-guard forbids.  Off-mesh, a plain
        jit."""
        import jax
        if self._mesh is None or out_shardings is None:
            return jax.jit(fn)
        return jax.jit(fn, out_shardings=out_shardings)

    def _out_shard_trees(self):
        """(kv_tree, repl) building blocks for out_shardings: one
        (k, v) sharding pair per block, and the replicated sharding
        for token outputs."""
        kv_pair = (self._kv_shard, self._kv_shard)
        return [kv_pair] * len(self.params["blocks"]), self._repl_shard

    def _make_pick(self):
        """In-graph seeded sampler (ISSUE 19), or None when greedy —
        the greedy programs keep their argmax bodies byte-identical to
        the pre-sampling build.  ``pick1(logits, seed, p)`` draws ONE
        token from a (vocab,) row with a counter-derived key folded
        from (engine sample stream, lane seed, absolute position p):
        the key depends on nothing else, so the tick, scan and while
        decode paths — spec or not, chunked or not — sample the
        identical token at the same position given the same seed."""
        if not self._sampling:
            return None
        import jax
        from veles_tpu.ops.transformer import sample_token
        base = xfer.to_device(self._sample_key_host)
        temp, topk = self.temperature, self.top_k

        def pick1(logits, seed, p):
            key = jax.random.fold_in(jax.random.fold_in(base, seed), p)
            return sample_token(key, logits, temp, topk)

        return pick1

    def _seed_args(self, seed):
        """Trailing scalar seed argument for a one-lane sampling
        dispatch (prefill/chunk) — empty when greedy, so the greedy
        program signatures stay exactly the pre-sampling ones."""
        if not self._sampling:
            return ()
        return (xfer.to_device(seed, numpy.int32),)

    def _seed_vec(self):
        """Trailing (slots,) lane-seed vector for the batched decode
        dispatches: each admitted lane's request seed, 0 for
        free/prefilling slots (their sampled garbage lands in masked or
        soon-overwritten writes, so the value never matters)."""
        if not self._sampling:
            return ()
        seeds = numpy.zeros(self.slots, numpy.int32)
        for slot, lane in enumerate(self._lanes):
            if lane is not None:
                seeds[slot] = lane.request.seed
        return (xfer.to_device(seeds),)

    # ------------------------------------------------------------- jitted core
    def _build_jits(self):
        import jax
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import (block_decode_step,
                                               chunk_apply, head_logits,
                                               prefill)
        n_heads, max_len = self.n_heads, self.max_len
        rope, window, sinks = self.rope, self.window, self.sinks
        C, k1 = self.prefill_chunk, self.spec_k + 1
        if self._paged:
            self._build_paged_jits()
            return
        pick1 = self._make_pick()

        def prefill_one(params, prompt, true_len, *sargs):
            # prompt (1, bucket) int32, true_len traced: positions
            # < true_len are exact under causal attention regardless of
            # pad content (see transformer._generate_impl), so one
            # compile serves every prompt length in the bucket
            h, caches = prefill(params, prompt, n_heads, max_len,
                                rope=rope, window=window, sinks=sinks)
            logits = head_logits(params, jax.lax.dynamic_slice_in_dim(
                h, true_len - 1, 1, axis=1))[:, 0, :]
            if pick1 is None:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            else:
                tok = pick1(logits[0], sargs[0], true_len)
            return tok, caches

        def install(caches, rows, slot):
            # scatter one prefilled lane (rows of (1,H,L,D)) into the
            # shared cache at a TRACED slot index — one compile total
            return [(k.at[slot].set(rk[0]), v.at[slot].set(rv[0]))
                    for (k, v), (rk, rv) in zip(caches, rows)]

        def step_one(params, cache_rows, tok, pos, seed=None):
            # one lane, one token: feed ``tok`` at ``pos`` against this
            # lane's cache rows; vmapped below over the slot axis so
            # every lane advances in ONE dispatch at its own position
            x = jnp.take(params["embed"], tok[None], axis=0)[None]
            if "pos" in params:
                x = x + jax.lax.dynamic_slice_in_dim(
                    params["pos"], pos, 1, axis=0)[None]
            new_rows = []
            for blk, (kc, vc) in zip(params["blocks"], cache_rows):
                x, kc, vc = block_decode_step(
                    blk, x, kc[None], vc[None], pos, n_heads, rope=rope,
                    window=window, sinks=sinks)
                new_rows.append((kc[0], vc[0]))
            logits = head_logits(params, x)[0, 0, :]
            if pick1 is None:
                return new_rows, jnp.argmax(logits).astype(jnp.int32)
            return new_rows, pick1(logits, seed, pos + 1)

        kv_tree = repl = None
        if self._mesh is not None:
            kv_tree, repl = self._out_shard_trees()
        step_all = jax.vmap(
            step_one, in_axes=(None, 0, 0, 0) if pick1 is None
            else (None, 0, 0, 0, 0))
        # programs: prefill
        self._prefill_jit = self._jit(
            prefill_one,
            (repl, kv_tree) if self._mesh is not None else None)
        # programs: install
        self._install_jit = self._jit(install, kv_tree)
        # programs: step
        self._step_jit = self._jit(
            step_all,
            (kv_tree, repl) if self._mesh is not None else None)

        self._chunk_jit = None
        self._chunk_install_jit = None
        self._chunk_extract_jit = None
        self._page_copy_jit = None
        if C:
            def chunk_slot(params, caches, tokens, slot, start,
                           last_idx, *sargs):
                # one prompt chunk for ONE lane, straight into the
                # shared caches at a TRACED (slot, start): positions
                # [start, start+C) computed against everything already
                # committed below them.  ``last_idx`` picks the chunk
                # offset whose next-token argmax to return (only read
                # on the final chunk).  One compile for every chunk of
                # every prompt length.
                rows = [(jax.lax.dynamic_slice_in_dim(kc, slot, 1, 0),
                         jax.lax.dynamic_slice_in_dim(vc, slot, 1, 0))
                        for kc, vc in caches]
                h, rows = chunk_apply(params, tokens[None], rows, start,
                                      n_heads, rope=rope, window=window,
                                      sinks=sinks)
                caches = [
                    (jax.lax.dynamic_update_slice(kc, rk,
                                                  (slot, 0, 0, 0)),
                     jax.lax.dynamic_update_slice(vc, rv,
                                                  (slot, 0, 0, 0)))
                    for (kc, vc), (rk, rv) in zip(caches, rows)]
                logits = head_logits(
                    params, jax.lax.dynamic_slice_in_dim(
                        h, last_idx, 1, axis=1))[:, 0, :]
                if pick1 is None:
                    tok = jnp.argmax(logits,
                                     axis=-1).astype(jnp.int32)[0]
                else:
                    tok = pick1(logits[0], sargs[0],
                                start + last_idx + 1)
                return caches, tok

            def chunk_extract(caches, slot, start):
                # copy one lane's chunk rows OUT (prefix-cache insert)
                return [
                    (jax.lax.dynamic_slice(
                        kc, (slot, 0, start, 0),
                        (1, kc.shape[1], C, kc.shape[3])),
                     jax.lax.dynamic_slice(
                        vc, (slot, 0, start, 0),
                        (1, vc.shape[1], C, vc.shape[3])))
                    for kc, vc in caches]

            def chunk_install(caches, rows, slot, start):
                # copy cached chunk rows IN (copy-on-install: the trie
                # entry and the lane's rows never alias)
                return [
                    (jax.lax.dynamic_update_slice(kc, rk,
                                                  (slot, 0, start, 0)),
                     jax.lax.dynamic_update_slice(vc, rv,
                                                  (slot, 0, start, 0)))
                    for (kc, vc), (rk, rv) in zip(caches, rows)]

            # programs: chunk
            self._chunk_jit = self._jit(
                chunk_slot,
                (kv_tree, repl) if self._mesh is not None else None)
            # programs: chunk_extract
            self._chunk_extract_jit = self._jit(chunk_extract, kv_tree)
            # programs: chunk_install
            self._chunk_install_jit = self._jit(chunk_install, kv_tree)

        self._verify_jit = None
        verify_all = None
        if self.spec_k:
            def verify_one(params, cache_rows, toks, pos, seed=None):
                # toks (k+1,) = [last committed, draft…] fed at
                # positions [pos, pos+k]; returns the greedy argmax
                # (or the seeded sample at each absolute position)
                # AFTER each fed token — the host accepts the longest
                # draft prefix that matches the verifier's own pick, so
                # output is exact by construction in both modes
                rows = [(kc[None], vc[None]) for kc, vc in cache_rows]
                h, rows = chunk_apply(params, toks[None], rows, pos,
                                      n_heads, rope=rope, window=window,
                                      sinks=sinks)
                logits = head_logits(params, h)[0]      # (k+1, vocab)
                if pick1 is None:
                    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    out = jax.vmap(pick1, in_axes=(0, None, 0))(
                        logits, seed, pos + 1 + jnp.arange(k1))
                return [(kc[0], vc[0]) for kc, vc in rows], out

            verify_all = jax.vmap(
                verify_one, in_axes=(None, 0, 0, 0) if pick1 is None
                else (None, 0, 0, 0, 0))
            # programs: verify
            self._verify_jit = self._jit(
                verify_all,
                (kv_tree, repl) if self._mesh is not None else None)

        # ---- decode megastep (ISSUE 13): K fused iterations of the
        # step (or propose→verify→accept) per dispatch — the scan body
        # IS the vmapped program above, so any K is bit-identical to K
        # repeated ticks; early-exit lanes freeze their carry (their
        # writes land at their own frozen in-bounds rows, harmless —
        # the lane is finished and its slot recycles at the boundary)
        self._wire_megastep_jit(kv_tree, repl, step_all=step_all,
                                verify_all=verify_all)

    def _build_paged_jits(self):
        """The PAGED program set — every shape is fixed by (slots,
        max_pages, chunk, k), so the whole mixed-length workload
        compiles exactly one program per family: ``_chunk_jit`` (one
        lane, one prompt chunk), ``_step_jit`` (every lane, one token,
        batched over the shared pool — vmap cannot carry a shared
        mutable pool, so the batching is explicit), ``_verify_jit``
        (every lane, k+1 speculative positions) and ``_page_copy_jit``
        (copy-on-write).  The whole-prompt prefill/install/extract
        programs have no paged counterpart (prefill is always chunked;
        prefix hits install page IDS, not rows).

        Two ISSUE 7 refinements: when the engine resolved
        ``attn_kernel`` active, every program's attention routes
        through the Pallas serving kernels ('prefill' for the chunk
        program, 'decode' for step/verify — same K/V writes, no
        materialized ``paged_view``); and step/verify accept tables
        SLICED to the live width ladder (one program per ladder entry,
        see ``_live_width``), so the per-token cost follows the batch's
        actual residency, not max_len."""
        import jax
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import (head_logits,
                                               paged_chunk_apply)
        n_heads = self.n_heads
        rope, window, sinks = self.rope, self.window, self.sinks
        kern = self._kernel_active
        pick1 = self._make_pick()

        def chunk_slot(params, pools, ptab, tokens, start, last_idx,
                       *sargs):
            # one lane's prompt chunk through its page table; returns
            # the pick after ``last_idx`` (read on the tail chunk)
            h, pools = paged_chunk_apply(
                params, tokens[None], pools, ptab[None], start[None],
                n_heads, rope=rope, window=window, sinks=sinks,
                attn_kernel="prefill" if kern else None)
            logits = head_logits(params, jax.lax.dynamic_slice_in_dim(
                h, last_idx, 1, axis=1))[:, 0, :]
            if pick1 is None:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            else:
                tok = pick1(logits[0], sargs[0], start + last_idx + 1)
            return pools, tok

        def step_all(params, pools, ptabs, toks, pos, *sargs):
            # ONE dispatch advances every lane by one token at its own
            # position through its own page table
            h, pools = paged_chunk_apply(
                params, toks[:, None], pools, ptabs, pos, n_heads,
                rope=rope, window=window, sinks=sinks,
                attn_kernel="decode" if kern else None)
            logits = head_logits(params, h)[:, 0, :]
            if pick1 is None:
                return pools, jnp.argmax(logits,
                                         axis=-1).astype(jnp.int32)
            return pools, jax.vmap(pick1)(logits, sargs[0], pos + 1)

        def page_copy(pools, src, dst):
            # copy-on-write: duplicate one page across every block so
            # the writer owns ``dst`` exclusively and the other
            # referents of ``src`` keep bit-identical rows
            return [(kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src]))
                    for kp, vp in pools]

        kv_tree = repl = None
        if self._mesh is not None:
            kv_tree, repl = self._out_shard_trees()
        pair = (kv_tree, repl) if self._mesh is not None else None
        # programs: chunk
        self._chunk_jit = self._jit(chunk_slot, pair)
        # programs: step
        self._step_jit = self._jit(step_all, pair)
        # programs: page_copy
        self._page_copy_jit = self._jit(page_copy, kv_tree)
        self._prefill_jit = None
        self._install_jit = None
        self._chunk_install_jit = None
        self._chunk_extract_jit = None
        self._verify_jit = None
        if self.spec_k:
            def verify_all(params, pools, ptabs, toks, pos, *sargs):
                # toks (slots, k+1) = [last committed, draft…] per lane;
                # returns the greedy argmax (or the seeded sample at
                # each absolute position) AFTER each fed position
                h, pools = paged_chunk_apply(
                    params, toks, pools, ptabs, pos, n_heads, rope=rope,
                    window=window, sinks=sinks,
                    attn_kernel="decode" if kern else None)
                logits = head_logits(params, h)      # (slots, k+1, v)
                if pick1 is None:
                    return pools, jnp.argmax(
                        logits, axis=-1).astype(jnp.int32)
                pp = pos[:, None] + 1 \
                    + jnp.arange(toks.shape[1])[None, :]
                return pools, jax.vmap(
                    jax.vmap(pick1, in_axes=(0, None, 0)))(
                        logits, sargs[0], pp)

            # programs: verify
            self._verify_jit = self._jit(verify_all, pair)

        # decode megastep (ISSUE 13): the fused K-iteration program —
        # the page-table slice stays a traced-data argument, so the
        # compile bound is one program per (live-width ladder entry × K)
        # family, K fixed per engine
        self._wire_megastep_jit(kv_tree, repl)

    # --------------------------------------------------------- megastep
    def _wire_megastep_jit(self, kv_tree, repl, step_all=None,
                           verify_all=None):
        """Build and jit the fused megastep program (or leave it None
        below K=2) — THE one wiring both layout builders share, so the
        output arity and the tp-mesh out_shardings pin (storage, last,
        pos, emitted[, accs]) can never drift between them.  ISSUE 19:
        megastep_mode='while' wires the early-exit lax.while_loop
        variant into ``_whilestep_jit`` instead — its own jit-guard
        census family (``whilestep``), its own output arity (storage,
        last, pos, emitted, iters[, accs][, assign])."""
        self._megastep_jit = None
        self._whilestep_jit = None
        if self.megastep < 2:
            return
        mega = self._make_megastep_body(step_all=step_all,
                                        verify_all=verify_all)
        if self.megastep_mode == "while":
            n_out = 5 + (1 if self.spec_k else 0) \
                + (1 if self.refill_ring else 0)
            out_sh = ((kv_tree,) + (repl,) * (n_out - 1)
                      if self._mesh is not None else None)
            # programs: whilestep
            self._whilestep_jit = self._jit(mega, out_sh)
            return
        n_out = 5 if self.spec_k else 4
        out_sh = ((kv_tree,) + (repl,) * (n_out - 1)
                  if self._mesh is not None else None)
        # programs: megastep
        self._megastep_jit = self._jit(mega, out_sh)

    def _make_megastep_body(self, step_all=None, verify_all=None):
        """Build the fused K-iteration decode program (ISSUE 13) for
        this engine's layout and speculation mode — the scan body IS
        the per-tick batched step (or propose → verify → accept leg),
        so any K is bit-identical to K repeated host ticks by
        construction.

        Signature of the returned function: ``(params, storage[,
        ptabs], last, pos, left[, hist, hlen]) -> (storage, last, pos,
        emitted[, accs])`` where ``storage`` is the contiguous caches
        or the paged pools, ``emitted`` is (K, slots) int32 — or
        (K, slots, spec_k+1) speculative — with -1 marking positions a
        frozen (early-exited or never-active) lane did not emit, and
        ``accs`` (K, slots) carries each iteration's draft-acceptance
        count (-1 when frozen) for the host's metering.

        EARLY-EXIT MASKING: a lane whose ``left`` hits 0 freezes — its
        last token, position and history stop advancing, its emitted
        slots read -1, and (paged) its K/V writes are redirected to
        the scratch page via ``write_mask`` so a dead iteration can
        never touch an allocated (possibly trie-shared) page.  On the
        contiguous layout frozen writes land at the lane's own frozen
        in-bounds row (the position clamp below keeps the speculative
        write window inside [0, max_len)), which is harmless: the lane
        is finished and its slot recycles at the boundary, exactly the
        existing free-/prefilling-slot garbage-write discipline.

        SPECULATIVE leg: the draft comes from
        ``ops/transformer.py::propose_draft_in_graph`` over a carried
        (slots, max_len) token-history buffer — accepted tokens are by
        construction the verifier's own argmax (``emit = out[:acc+1]``,
        since a draft token only counts as accepted when it EQUALS the
        argmax), so greedy output is exact whatever the draft, and
        spec_k composes with the megastep at zero host round-trips."""
        import jax
        import jax.numpy as jnp
        K, k = self.megastep, self.spec_k
        paged = self._paged
        n_heads = self.n_heads
        rope, window, sinks = self.rope, self.window, self.sinks
        kern = self._kernel_active
        L = self.max_len
        slots = self.slots
        pick1 = self._make_pick()
        sampling = pick1 is not None
        R = self.refill_ring if self.megastep_mode == "while" else 0
        if paged:
            from veles_tpu.ops.transformer import (head_logits,
                                                   paged_chunk_apply)
        # frozen-lane feed clamp: an active lane's legitimate feed
        # positions never reach it (admission reserves n_new + spec_k
        # headroom), and a finished lane's garbage verify window
        # [pos, pos+k] must stay inside [0, max_len)
        cap = xfer.to_device(L - 1 - k, numpy.int32)

        if k:
            from veles_tpu.ops.transformer import propose_draft_in_graph
            ngram = self.spec_ngram
            propose_all = jax.vmap(
                lambda h, hl: propose_draft_in_graph(h, hl, k, ngram))
            cols = xfer.to_device(numpy.arange(k + 1)[None, :])

            def spec_iter(params, storage, ptabs, seeds, carry):
                last, pos, left, hist, hlen = carry
                active = left > 0
                draft, _found = propose_all(hist, hlen)
                toks = jnp.concatenate([last[:, None], draft], axis=1)
                if paged:
                    h, storage = paged_chunk_apply(
                        params, toks, storage, ptabs, pos, n_heads,
                        rope=rope, window=window, sinks=sinks,
                        attn_kernel="decode" if kern else None,
                        write_mask=active)
                    logits = head_logits(params, h)
                    if pick1 is None:
                        out = jnp.argmax(logits,
                                         axis=-1).astype(jnp.int32)
                    else:
                        out = jax.vmap(jax.vmap(
                            pick1, in_axes=(0, None, 0)))(
                            logits, seeds, pos[:, None] + 1 + cols)
                elif pick1 is None:
                    storage, out = verify_all(params, storage, toks,
                                              pos)
                else:
                    storage, out = verify_all(params, storage, toks,
                                              pos, seeds)
                # leading draft/argmax matches; accepted tokens ARE
                # out[:acc], so the emit window is simply out[:take]
                matches = (draft == out[:, :k]).astype(jnp.int32)
                acc = jnp.cumprod(matches, axis=1).sum(axis=1)
                take = jnp.minimum(acc + 1, left)
                emit = jnp.where(
                    active[:, None] & (cols < take[:, None]), out, -1)
                # history append: the full (k+1) window lands at hlen
                # (start clamped so the update can never shift); rows
                # past `take` are overwritten by the next append or
                # never read — draft quality is speed-only
                hist = jax.vmap(
                    lambda h_, hl, row, act: jnp.where(
                        act, jax.lax.dynamic_update_slice(
                            h_, row,
                            (jnp.minimum(hl, L - (k + 1)),)), h_))(
                    hist, hlen, out, active)
                hlen = jnp.where(active,
                                 jnp.minimum(hlen + take, L), hlen)
                last = jnp.where(active, jnp.take_along_axis(
                    out, acc[:, None], axis=1)[:, 0], last)
                pos = jnp.where(active,
                                jnp.minimum(pos + acc + 1, cap), pos)
                left = left - jnp.where(active, take, 0)
                return storage, (last, pos, left, hist, hlen), \
                    (emit, jnp.where(active, acc, -1))

            if self.megastep_mode != "while":
                def mega_spec(params, storage, ptabs, last, pos, left,
                              hist, hlen, *sargs):
                    seeds = sargs[0] if sampling else None

                    def body(carry, _):
                        storage, rest = carry
                        storage, rest, out = spec_iter(
                            params, storage, ptabs, seeds, rest)
                        return (storage, rest), out

                    (storage, rest), (emitted, accs) = jax.lax.scan(
                        body, (storage, (last, pos, left, hist, hlen)),
                        None, length=K)
                    return storage, rest[0], rest[1], emitted, accs

                if paged:
                    return mega_spec
                return lambda params, storage, *a: mega_spec(
                    params, storage, None, *a)

        if not k:
            def plain_iter(params, storage, ptabs, seeds, carry):
                last, pos, left = carry
                active = left > 0
                if paged:
                    h, storage = paged_chunk_apply(
                        params, last[:, None], storage, ptabs, pos,
                        n_heads, rope=rope, window=window, sinks=sinks,
                        attn_kernel="decode" if kern else None,
                        write_mask=active)
                    logits = head_logits(params, h)[:, 0, :]
                    if pick1 is None:
                        toks = jnp.argmax(logits,
                                          axis=-1).astype(jnp.int32)
                    else:
                        toks = jax.vmap(pick1)(logits, seeds, pos + 1)
                elif pick1 is None:
                    storage, toks = step_all(params, storage, last,
                                             pos)
                else:
                    storage, toks = step_all(params, storage, last,
                                             pos, seeds)
                emit = jnp.where(active, toks, -1)
                last = jnp.where(active, toks, last)
                pos = jnp.where(active, pos + 1, pos)
                left = left - jnp.where(active, 1, 0)
                return storage, (last, pos, left), emit

            if self.megastep_mode != "while":
                def mega_plain(params, storage, ptabs, last, pos, left,
                               *sargs):
                    seeds = sargs[0] if sampling else None

                    def body(carry, _):
                        storage, rest = carry
                        storage, rest, emit = plain_iter(
                            params, storage, ptabs, seeds, rest)
                        return (storage, rest), emit

                    (storage, rest), emitted = jax.lax.scan(
                        body, (storage, (last, pos, left)), None,
                        length=K)
                    return storage, rest[0], rest[1], emitted

                if paged:
                    return mega_plain
                return lambda params, storage, *a: mega_plain(
                    params, storage, None, *a)

        # ---- ISSUE 19: the persistent-loop variant — same iteration
        # body, but driven by lax.while_loop so the program EXITS as
        # soon as every live lane (and the published standby ring) is
        # drained instead of burning masked iterations to the K
        # boundary.  Stacked per-iteration outputs land in a fixed
        # (K, ...) buffer via dynamic_update_slice (while_loop has no
        # scan-style stacking), so the output shapes — and the program
        # family — stay exactly the scan megastep's.  Idle slots enter
        # with left = -1 so only a slot that DRAINED (left hit 0 from
        # a positive value, or was published as re-armable) can take a
        # standby entry.
        def mega_while(params, storage, ptabs, last, pos, left, *rest):
            rest = list(rest)
            if k:
                hist, hlen = rest.pop(0), rest.pop(0)
            seeds = rest.pop(0) if sampling else None
            if R:
                ring_tabs, ring_last = rest.pop(0), rest.pop(0)
                ring_pos, ring_left = rest.pop(0), rest.pop(0)
                if k:
                    ring_hist, ring_hlen = rest.pop(0), rest.pop(0)
                if sampling:
                    ring_seeds = rest.pop(0)
                count = rest.pop(0)
            c = {"storage": storage, "ptabs": ptabs, "last": last,
                 "pos": pos, "left": left, "i": jnp.int32(0),
                 "emitted": jnp.full((K, slots, k + 1) if k
                                     else (K, slots), -1, jnp.int32)}
            if k:
                c["hist"], c["hlen"] = hist, hlen
                c["accs"] = jnp.full((K, slots), -1, jnp.int32)
            if sampling:
                c["seeds"] = seeds
            if R:
                c["head"] = jnp.int32(0)
                c["assign"] = jnp.full((R,), -1, jnp.int32)

            def cond(c):
                live = jnp.any(c["left"] > 0)
                if R:
                    live = live | (c["head"] < count)
                return (c["i"] < K) & live

            def body(c):
                c = dict(c)
                if R:
                    # in-graph re-arm: each drained slot (left == 0)
                    # takes the next unconsumed ring entry — frontier,
                    # page-table row, history and seed all swap in one
                    # masked select; ``assign`` records entry -> slot
                    # so the host can attribute the emitted rows at
                    # the boundary.  Unrolled over the small slot
                    # count; at most one entry arms per slot per
                    # iteration, which is exact (a slot drains at most
                    # once per iteration).
                    for s in range(slots):
                        idx = jnp.minimum(c["head"], R - 1)
                        take = (c["left"][s] == 0) & \
                            (c["head"] < count)
                        c["ptabs"] = jnp.where(
                            take,
                            c["ptabs"].at[s].set(ring_tabs[idx]),
                            c["ptabs"])
                        c["last"] = c["last"].at[s].set(jnp.where(
                            take, ring_last[idx], c["last"][s]))
                        c["pos"] = c["pos"].at[s].set(jnp.where(
                            take, ring_pos[idx], c["pos"][s]))
                        c["left"] = c["left"].at[s].set(jnp.where(
                            take, ring_left[idx], c["left"][s]))
                        if k:
                            c["hist"] = jnp.where(
                                take,
                                c["hist"].at[s].set(ring_hist[idx]),
                                c["hist"])
                            c["hlen"] = c["hlen"].at[s].set(
                                jnp.where(take, ring_hlen[idx],
                                          c["hlen"][s]))
                        if sampling:
                            c["seeds"] = c["seeds"].at[s].set(
                                jnp.where(take, ring_seeds[idx],
                                          c["seeds"][s]))
                        c["assign"] = c["assign"].at[idx].set(
                            jnp.where(take, s, c["assign"][idx]))
                        c["head"] = c["head"] + take.astype(jnp.int32)
                if k:
                    carry = (c["last"], c["pos"], c["left"],
                             c["hist"], c["hlen"])
                    c["storage"], carry, (emit, acc) = spec_iter(
                        params, c["storage"], c["ptabs"],
                        c.get("seeds"), carry)
                    (c["last"], c["pos"], c["left"], c["hist"],
                     c["hlen"]) = carry
                    c["accs"] = jax.lax.dynamic_update_slice(
                        c["accs"], acc[None], (c["i"], 0))
                    c["emitted"] = jax.lax.dynamic_update_slice(
                        c["emitted"], emit[None], (c["i"], 0, 0))
                else:
                    carry = (c["last"], c["pos"], c["left"])
                    c["storage"], carry, emit = plain_iter(
                        params, c["storage"], c["ptabs"],
                        c.get("seeds"), carry)
                    c["last"], c["pos"], c["left"] = carry
                    c["emitted"] = jax.lax.dynamic_update_slice(
                        c["emitted"], emit[None], (c["i"], 0))
                c["i"] = c["i"] + 1
                return c

            # programs: whilestep
            c = jax.lax.while_loop(cond, body, c)
            res = [c["storage"], c["last"], c["pos"], c["emitted"],
                   c["i"]]
            if k:
                res.append(c["accs"])
            if R:
                res.append(c["assign"])
            return tuple(res)

        if paged:
            return mega_while
        return lambda params, storage, *a: mega_while(
            params, storage, None, *a)

    # --------------------------------------------------------------- lifecycle
    def _warmup(self):
        """Compile every program family before traffic, with every
        dispatch argument an explicit transfer (xfer shims) — the
        first code to run under the armed transfer guard."""
        zero = xfer.to_device(0, numpy.int32)
        zeros = xfer.to_device(numpy.zeros(self.slots, numpy.int32))
        # seeded sampling appends a trailing seed argument per program
        # family (scalar for the one-lane programs, a lane vector for
        # the batched ones) — warm with it or the first sampled
        # dispatch compiles inside the serving loop
        s1 = (zero,) if self._sampling else ()
        sv = (zeros,) if self._sampling else ()
        if self._paged:
            ptabs = numpy.zeros((self.slots, self._max_pages),
                                numpy.int32)
            self._kv_pools, _ = self._chunk_jit(
                self.params, self._kv_pools, xfer.to_device(ptabs[0]),
                xfer.to_device(numpy.zeros(self.prefill_chunk,
                                           numpy.int32)), zero, zero,
                *s1)
            self._kv_pools = self._page_copy_jit(self._kv_pools, zero,
                                                 zero)
            # step/verify (or the fused megastep / whilestep, which
            # REPLACES them on the decode loop) compile one program per
            # live-width ladder entry (ISSUE 7) — warm EVERY entry now,
            # or the first request to cross each width boundary pays
            # its compile inside the serving loop
            for w in self._width_ladder:
                wtab = xfer.to_device(ptabs[:, :w])
                fused = self._whilestep_jit or self._megastep_jit
                if fused is not None:
                    args = [self.params, self._kv_pools, wtab,
                            zeros, zeros, zeros]
                    if self.spec_k:
                        args += [xfer.to_device(numpy.zeros(
                            (self.slots, self.max_len), numpy.int32)),
                            zeros]
                    args += sv
                    if self._whilestep_jit is not None \
                            and self.refill_ring:
                        args += self._ring_zero_args(w)
                    out = fused(*args)
                    self._kv_pools = out[0]
                    continue
                if self._verify_jit is not None:
                    self._kv_pools, _ = self._verify_jit(
                        self.params, self._kv_pools, wtab,
                        xfer.to_device(numpy.zeros(
                            (self.slots, self.spec_k + 1),
                            numpy.int32)), zeros, *sv)
                self._kv_pools, _ = self._step_jit(
                    self.params, self._kv_pools, wtab, zeros, zeros,
                    *sv)
        else:
            tok, rows = self._prefill_jit(
                self.params,
                xfer.to_device(numpy.zeros(
                    (1, prompt_bucket(1, self.max_len)), numpy.int32)),
                xfer.to_device(1, numpy.int32), *s1)
            self._caches = self._install_jit(self._caches, rows, zero)
            if self._chunk_jit is not None:
                self._caches, _ = self._chunk_jit(
                    self.params, self._caches,
                    xfer.to_device(numpy.zeros(self.prefill_chunk,
                                               numpy.int32)), zero,
                    zero, zero, *s1)
                crows = self._chunk_extract_jit(self._caches, zero,
                                                zero)
                self._caches = self._chunk_install_jit(self._caches,
                                                       crows, zero,
                                                       zero)
            fused = self._whilestep_jit or self._megastep_jit
            if fused is not None:
                args = [self.params, self._caches, zeros, zeros, zeros]
                if self.spec_k:
                    args += [xfer.to_device(numpy.zeros(
                        (self.slots, self.max_len), numpy.int32)),
                        zeros]
                args += sv
                self._caches = fused(*args)[0]
            else:
                if self._verify_jit is not None:
                    self._caches, _ = self._verify_jit(
                        self.params, self._caches,
                        xfer.to_device(numpy.zeros(
                            (self.slots, self.spec_k + 1),
                            numpy.int32)), zeros, *sv)
                self._caches, _ = self._step_jit(
                    self.params, self._caches, zeros,
                    xfer.to_device(numpy.ones(self.slots,
                                              numpy.int32)), *sv)

    def start(self):
        # warm every program before traffic: the discarded warmup
        # writes land at positions of free slots (paged: the scratch
        # page) that the next prefill/chunk overwrites — or a live
        # mask excludes — before they are ever attended.  Warmup runs
        # under the transfer-guard witness (dispatch arguments built
        # through the explicit xfer shims, like the worker loop).
        with xfer.guard():
            self._warmup()
        with self._cond:
            self._stop = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="lm-engine-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None

    # ---------------------------------------------------------------- hot swap
    def _check_swap_structure(self, params):
        """Refuse a structurally incompatible tree LOUDLY before
        anything is placed: the compiled programs are specialized on
        the current shapes/dtypes, so a mismatch would either recompile
        every family mid-traffic or crash a dispatch.  Old weights keep
        serving on refusal — nothing is touched here."""
        import jax
        from jax.tree_util import keystr, tree_flatten_with_path
        old, old_def = tree_flatten_with_path(self.params)
        new, new_def = jax.tree_util.tree_flatten(params)
        if old_def != new_def:
            raise ValueError(
                "swap refused: new param tree structure differs from "
                "the serving tree (%s vs %s) — old weights keep "
                "serving" % (new_def, old_def))
        for (path, o), n in zip(old, new):
            shape = tuple(getattr(n, "shape", ()) or ())
            dtype = getattr(n, "dtype", None)
            if shape != tuple(o.shape) or dtype != o.dtype:
                raise ValueError(
                    "swap refused: param %s is %s%s but the serving "
                    "tree holds %s%s — old weights keep serving"
                    % (keystr(path), shape, dtype, tuple(o.shape),
                       o.dtype))

    def swap_weights(self, params, version=None, drain=False,
                     timeout_s=120.0):
        """Hot-install ``params`` (same structure/shapes/dtypes as the
        serving tree) into this LIVE engine without dropping lanes.

        The tree is validated and ``device_put`` under the engine's
        existing placement HERE, on the caller's thread (off the decode
        hot path; tp engines re-shard by ``lm_param_specs`` shard-by-
        shard); the worker applies the swap at a tick boundary.  By
        default in-flight lanes FINISH on the old weights first —
        admission holds while they do, the old tree stays pinned until
        its last lane completes, and the apply itself is one pointer
        assignment (no decode tick stalls longer than a step).  With
        ``drain=True`` active lanes are withdrawn whole and re-queued
        at the head instead: they re-decode from scratch on the new
        weights, resolving their (unchanged) futures exactly once.

        ``version`` (int; default: current + 1) becomes
        :attr:`weights_version` — stamped on every result produced by
        the new weights and exported as the ``weights_version`` gauge.
        Returns the installed version; raises ValueError on structural
        mismatch and re-raises an apply-time fault (``engine.swap``
        site), in both cases leaving the old weights serving.  Blocks
        until applied (``timeout_s`` bounds a wedged worker)."""
        self._check_swap_structure(params)
        placed = self._place_params(params)
        if version is None:
            version = self.weights_version + 1
        version = int(version)
        with self._cond:
            if self._pending_swap is not None:
                raise RuntimeError("a weight swap is already in flight")
            if self._thread is None or self._stop:
                # not serving: apply directly (start() warms the new
                # tree like any other)
                self.params = placed
                self._set_version(version)
                self.metrics.inc("weight_swaps")
                return version
            swap = {"params": placed, "version": version,
                    "drain": bool(drain), "done": threading.Event(),
                    "exc": None, "t0": time.monotonic()}
            self._pending_swap = swap
            self._cond.notify_all()
        if not swap["done"].wait(timeout_s):
            with self._cond:
                withdrawn = self._pending_swap is swap
                if withdrawn:
                    self._pending_swap = None
            if not withdrawn:
                # the worker CLAIMED the swap right at the deadline —
                # the apply is a pointer assignment, so give it a
                # moment rather than reporting a state we know is
                # about to be wrong
                swap["done"].wait(5.0)
            if not swap["done"].is_set():
                raise RuntimeError(
                    "weight swap did not apply within %.0fs (worker "
                    "wedged or lanes never finished); %s"
                    % (timeout_s,
                       "old weights keep serving" if withdrawn else
                       "swap state INDETERMINATE — the worker claimed "
                       "it but never finished applying"))
        if swap["exc"] is not None:
            raise swap["exc"]
        return version

    def _set_version(self, version):
        self.weights_version = int(version)
        self.metrics.set_gauge("weights_version", self.weights_version)

    def _peek_swap(self):
        """Racy worker peek at the pending weight swap.  Read-only:
        every consumer that acts on the result re-checks (and claims)
        under ``_cond`` — ``_admit``/``_admit_ring``/``_advance_ring``/
        ``_step_while`` only use it to hold work back for a tick, and
        ``_maybe_apply_swap`` re-validates identity before claiming."""
        # lint: allow(lock-discipline): racy worker peek; claim re-checked under _cond
        return self._pending_swap

    def _maybe_apply_swap(self):
        """Worker-side swap application (one is-None check per tick).
        Finish-on-old waits for the active lanes (admission is held in
        ``_admit`` so the wait is bounded by their remaining n_new);
        drain mode re-queues them whole first.  The apply itself is a
        pointer assignment — the tree was placed on the caller's
        thread."""
        swap = self._peek_swap()
        if swap is None:
            return
        active = [i for i, lane in enumerate(self._lanes)
                  if lane is not None]
        if active and not swap["drain"]:
            return           # lanes finish on the OLD weights first
        with self._cond:
            # CLAIM before mutating anything: a timed-out caller may
            # have withdrawn the swap — applying (or requeueing lanes
            # for) a withdrawn swap would serve weights the caller was
            # told never installed
            if self._pending_swap is not swap:
                return
            self._pending_swap = None
        if active:
            self._requeue_active(active)
        if self._ring:
            # standby prefill ran on the OLD weights — stale KV the
            # moment the new tree installs
            self._requeue_ring()
        t0a = time.monotonic()
        try:
            self._fault("engine.swap")
            self.params = swap["params"]
        except Exception as e:   # noqa: BLE001 — refuse, keep serving
            swap["exc"] = e
            self.metrics.record_error()
            self.metrics.inc("weight_swap_failures")
            self.warning("weight swap refused at apply: %s (old "
                         "weights keep serving)", e)
            if self._tracer is not None:
                self._tracer.event(
                    "swap.refused", cat="swap", t0=t0a,
                    attrs={"engine": self.name, "error": str(e)})
        else:
            self._set_version(swap["version"])
            self.metrics.inc("weight_swaps")
            self.metrics.set_gauge("swap_quiesce_s",
                                   time.monotonic() - swap["t0"])
            if self._tracer is not None:
                self._tracer.event(
                    "swap.apply", cat="swap", t0=t0a,
                    attrs={"engine": self.name,
                           "version": swap["version"],
                           "drain": swap["drain"],
                           "quiesce_s": round(
                               time.monotonic() - swap["t0"], 4)})
        swap["done"].set()

    def _requeue_active(self, active):
        """Drain-mode swap: withdraw every active lane WHOLE and put
        its request back at the queue head in original admission order
        — the engine-internal analogue of the router's drain
        re-placement.  The futures are untouched: each request
        re-decodes from scratch (on the new weights) and resolves
        exactly once."""
        order = sorted(active,
                       key=lambda s: self._lanes[s].request.t_enq)
        reqs = []
        fresh_deadline = time.monotonic() + self.deadline_s
        for slot in order:
            lane = self._lanes[slot]
            self._vacate_slot(slot, lane)
            # the re-decode gets a fresh admission-sized budget: the
            # request already spent its wait DECODING — shedding it
            # 503 at its original deadline would turn the deploy into
            # a client-visible error
            lane.request.deadline = max(lane.request.deadline,
                                        fresh_deadline)
            req = lane.request
            if req.trace is not None:
                req.trace.tracer.instant(
                    req.trace, "swap.requeue", cat="engine")
                # back in the queue: a fresh queue-wait span, ended by
                # the re-admission like any other
                req.tspan = req.trace.tracer.begin(
                    req.trace, "queue.wait", cat="queue",
                    attrs={"engine": self.name, "requeued": True})
            reqs.append(req)
        with self._cond:
            for req in reversed(reqs):
                self._queue.appendleft(req)
                self._queued_tokens += req.true_len
                self._queued_pages += req.pages
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self.metrics.set_gauge("queue_tokens", self._queued_tokens)
            if self._paged:
                self.metrics.set_gauge("queue_pages",
                                       self._queued_pages)
        self.metrics.inc("requests_requeued_for_swap", len(reqs))

    # ------------------------------------------------------------------ client
    def submit(self, prompt, n_new):
        """Queue one prompt ((s,) ints) for ``n_new`` greedy tokens;
        returns a Future resolving to the (n_new,) continuation."""
        prompt = numpy.asarray(prompt, numpy.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        if len(prompt) + n_new + self.spec_k > self.max_len:
            extra = (" (+%d speculative headroom, spec_k)" % self.spec_k
                     if self.spec_k else "")
            raise ValueError("prompt %d + n_new %d%s exceeds the engine "
                             "cache length %d"
                             % (len(prompt), n_new, extra, self.max_len))
        demand = 0
        if self._paged:
            span = len(prompt) + n_new + self.spec_k
            demand = -(-span // self.prefill_chunk)
            if demand > self._pool.num_pages:
                raise ValueError(
                    "prompt %d + n_new %d needs %d KV pages but the "
                    "pool holds %d — this request can never be placed"
                    % (len(prompt), n_new, demand,
                       self._pool.num_pages))
        self._fault("engine.submit")
        # tracing (ISSUE 12): join the caller's request context (HTTP /
        # router) or root one here (direct engine use, benches) —
        # whoever STARTED the trace finishes it, so own_root marks
        # ours; a sampled-out decision anywhere above sticks
        tctx, own_root = None, False
        if self._tracer is not None:
            tctx, own_root = tracing.join_or_root(
                self._tracer, "engine.request", "engine",
                attrs={"engine": self.name})
            if tctx is tracing.SAMPLED_OUT:
                tctx = None
        try:
            return self._submit_admit(prompt, n_new, demand, tctx,
                                      own_root)
        except Exception as e:
            if own_root:
                tctx.tracer.finish_request(tctx, error=e)
            raise

    def _submit_admit(self, prompt, n_new, demand, tctx, own_root):
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("LM engine is not running")
            if len(self._queue) >= self.queue_depth:
                self.metrics.record_reject()
                raise Overloaded()
            if self.queue_tokens and self._queue and \
                    self._queued_tokens + len(prompt) > self.queue_tokens:
                # prompt-length budgeting: queued PREFILL WORK is
                # bounded, not just request count — a burst of long
                # prompts sheds early instead of stacking seconds of
                # head-of-line prefill behind the queue
                self.metrics.record_reject()
                self.metrics.inc("rejected_tokens", len(prompt))
                raise Overloaded()
            if self._paged and self._queue and \
                    self._queued_pages + demand > 2 * self._pool.num_pages:
                # pool-pressure admission: once TWO full pools' worth
                # of page demand is queued (one generation decoding,
                # one waiting), a new arrival would only sit until its
                # deadline — 429 it NOW with an exception that names
                # the resource (never a hang; the head request always
                # enqueues, so a single large request cannot wedge an
                # empty queue)
                self.metrics.record_reject()
                self.metrics.inc("rejected_pages", demand)
                raise PoolExhausted(demand, 2 * self._pool.num_pages)
            req = _Request(prompt, int(n_new), self.deadline_s,
                           pages=demand)
            if tctx is not None:
                req.trace = tctx
                req.tspan = tctx.tracer.begin(
                    tctx, "queue.wait", cat="queue",
                    attrs={"engine": self.name})
                if own_root:
                    req.future.add_done_callback(
                        lambda f, ctx=tctx:
                        tracing.finish_from_future(ctx, f))
            # admission journal (ISSUE 10): the entry lives until the
            # request's future settles (result, exception or cancel) —
            # checkpoint() snapshots exactly the unresolved set.  The
            # pop re-takes the (reentrant) engine lock so a concurrent
            # checkpoint never iterates a mutating dict.
            self._rid += 1
            rid = self._rid
            # seeded-sampling lane seed (ISSUE 19): the admission id is
            # deterministic per submission order, so the same traffic
            # replayed against any engine config (tick/scan/while,
            # paged or contiguous, tp=1/2) folds the SAME (seed, pos)
            # coordinates into the sampling stream — that is what the
            # seeded-parity matrix asserts
            req.seed = rid
            self._journal[rid] = req
            req.future.add_done_callback(
                lambda f, rid=rid: self._journal_pop(rid))
            self._queue.append(req)
            self._queued_tokens += req.true_len
            self._queued_pages += req.pages
            self.metrics.record_enqueue()
            self.metrics.set_gauge("queue_depth", len(self._queue))
            # the router/bench-visible high-water mark of this
            # replica's backlog (an instantaneous gauge under-reads
            # between scrapes)
            self.metrics.set_gauge_max("queue_depth_peak",
                                       len(self._queue))
            self.metrics.set_gauge("queue_tokens", self._queued_tokens)
            if self._paged:
                self.metrics.set_gauge("queue_pages",
                                       self._queued_pages)
            self._cond.notify()
        return req.future

    def generate(self, prompts, n_new, return_versions=False):
        """Decode a whole (b, s) prompt batch; returns (b, s + n_new)
        int32 — prompt plus greedy continuation per row (rows decode
        concurrently across slots; with ``return_versions`` also the
        ``weights_version`` that served each row — rows straddling a
        hot swap carry different stamps).  All-or-nothing: if a later
        row is refused (Overloaded/...), the rows already queued are
        CANCELLED instead of decoding to discarded results — a rejected
        batch must not keep consuming slots exactly when the engine is
        overloaded."""
        prompts = numpy.asarray(prompts, numpy.int32)
        futures = []
        try:
            for row in prompts:
                futures.append(self.submit(row, n_new))
            news = numpy.stack([f.result() for f in futures])
        except Exception:
            # one row refused (Overloaded) or failed (shed, prefill
            # fault): withdraw ALL siblings — they must not keep
            # consuming slots for output nobody will read
            for f in futures:
                self._cancel(f.request)
            raise
        out = numpy.concatenate([prompts, news], axis=1)
        if return_versions:
            return out, [getattr(f, "version", None) for f in futures]
        return out

    def _cancel(self, req):
        """Withdraw a request: dequeue it if still queued; if already in
        a slot, flag it so the worker frees the slot at the next tick."""
        req.cancelled = True
        with self._cond:
            try:
                self._queue.remove(req)
                self._queued_tokens -= req.true_len
                self._queued_pages -= req.pages
            except ValueError:
                return           # admitted (or done) — worker handles it
        self._trace_queue_end(req, "cancelled")
        req.future.cancel()

    # --------------------------------------------------- crash-safe recovery
    def _journal_pop(self, rid):
        with self._cond:
            self._journal.pop(rid, None)

    def checkpoint(self):
        """JSON-safe snapshot of the HOST-side serving state (ISSUE
        10): every ADMITTED-but-unresolved request (the admission
        journal), the slot frontiers, and — paged — the page tables
        and the pool's full ref/pin/free bookkeeping.  Taken under the
        engine lock, so the request set is consistent; cheap enough to
        take per admission tick.

        A crash loses DEVICE state (KV rows) unconditionally, so the
        checkpoint deliberately carries no tensors: :meth:`restore`
        re-admits the journaled work on a fresh engine and prefill
        re-derives the KV — greedy decode is deterministic, so the
        resumed outputs are bit-identical to what the crashed engine
        would have served.  The pool/page-table sections exist for
        POST-MORTEM diagnostics (what the allocator looked like at
        the crash), not for reattachment — and since the worker
        mutates the allocator without this lock, they can be torn
        mid-tick on a LIVE engine: treat them as best-effort evidence
        (a phantom inconsistency in a live-traffic snapshot is the
        tear, not a leak); only the request set is exact.
        :meth:`restore` never reads them."""
        with self._cond:
            entries = [{"rid": rid,
                        "prompt": [int(t) for t in req.prompt],
                        "n_new": int(req.n_new)}
                       for rid, req in sorted(self._journal.items())
                       if not req.future.done() and not req.cancelled]
            state = {
                "format": 1,
                "config": {"max_len": self.max_len,
                           "slots": self.slots,
                           "prefill_chunk": self.prefill_chunk,
                           "spec_k": self.spec_k,
                           "paged_kv": bool(self._paged),
                           "pool_pages": (self._pool.num_pages
                                          if self._paged else 0)},
                "requests": entries,
                "slot_frontiers": {
                    "pos": [int(x) for x in self._pos],
                    "last": [int(x) for x in self._last]},
            }
            if self._paged:
                state["pool"] = self._pool.snapshot()
                state["page_tables"] = self._page_tables.tolist()
            if self._trie is not None:
                state["prefix_cache_chunks"] = self._trie.size
        return state

    def restore(self, state):
        """Re-admit a :meth:`checkpoint`'s unresolved requests into
        THIS (fresh, already :meth:`start`-ed) engine after a crash:
        verifies the new pool's allocator invariants first (a restore
        must never begin on a corrupt pool), then submits each
        journaled request afresh.  Returns ``{rid: Future}`` so the
        supervisor can hand results back to whoever was waiting.

        In-flight-at-crash work is resumed AT-LEAST-ONCE from the
        engine's point of view (a request that completed between the
        checkpoint and the crash re-runs); exactly-once delivery is
        the caller's layer (the router's drain/requeue discipline —
        an old future that already delivered is simply gone with the
        crashed process)."""
        if not isinstance(state, dict) or state.get("format") != 1:
            raise ValueError("not an LMEngine checkpoint (format %r)"
                             % (state.get("format")
                                if isinstance(state, dict) else state))
        cfg = state.get("config", {})
        if int(cfg.get("max_len", self.max_len)) > self.max_len:
            raise ValueError(
                "checkpoint was taken at max_len %d but this engine "
                "holds %d — journaled prompts may not fit"
                % (cfg["max_len"], self.max_len))
        self.verify_pool_invariants()
        futures = {}
        entries = list(state.get("requests", ()))
        # validate EVERY entry against this engine's geometry before
        # admitting ANY: a structural refusal (span beyond max_len, a
        # page demand the restoring pool can never cover) must be an
        # all-or-nothing ValueError up front, not a mid-loop escape
        # that strands already-re-admitted futures
        for entry in entries:
            span = len(entry["prompt"]) + int(entry["n_new"]) \
                + self.spec_k
            if span > self.max_len:
                raise ValueError(
                    "journaled request rid=%s needs %d cache positions "
                    "but this engine holds %d"
                    % (entry.get("rid"), span, self.max_len))
            if self._paged and -(-span // self.prefill_chunk) \
                    > self._pool.num_pages:
                raise ValueError(
                    "journaled request rid=%s needs %d KV pages but "
                    "this engine's pool holds %d — restore into a "
                    "pool at least as large as the checkpoint's "
                    "(pool_pages=%s)"
                    % (entry.get("rid"),
                       -(-span // self.prefill_chunk),
                       self._pool.num_pages, cfg.get("pool_pages")))
        # a full-at-crash journal can exceed the fresh queue's capacity
        # momentarily — the worker drains it, so re-admission is a
        # closed loop honoring Retry-After, never a partial restore
        # that strands already-admitted futures on an exception
        stop = time.monotonic() + 30.0
        for entry in entries:
            while True:
                try:
                    futures[entry["rid"]] = self.submit(
                        entry["prompt"], entry["n_new"])
                    break
                except Overloaded as e:
                    if time.monotonic() > stop:
                        raise RuntimeError(
                            "restore stalled: %d/%d journaled requests "
                            "re-admitted before the engine stopped "
                            "accepting" % (len(futures), len(entries)))
                    time.sleep(min(getattr(e, "retry_after", 0.05),
                                   0.05))
        self.metrics.inc("engine_restores")
        self.metrics.inc("requests_restored", len(futures))
        return futures

    def verify_pool_invariants(self):
        """Cross-check the paged allocator against the engine's OWN
        references (ISSUE 10): every page's refcount must equal the
        lane references (one per lane holding it, each also pinned)
        plus the trie references (one per node storing it), and the
        pool's internal free-list/ref/pin bookkeeping must be
        self-consistent.  Raises RuntimeError naming the first
        violated page; returns a summary dict when sound.  Call
        quiesced (no worker mid-tick) — the chaos tests run it after
        traffic drains and after restore."""
        if not self._paged:
            return {"paged": False}
        self._pool.verify()
        n = self._pool.num_pages
        want_refs = [0] * (n + 1)
        want_pins = [0] * (n + 1)
        for lane in self._lanes:
            if lane is None:
                continue
            for p in lane.pages:
                want_refs[p] += 1
                want_pins[p] += 1
        for entry in self._ring:
            # standby-ring occupants hold pages exactly like lanes
            # (ISSUE 19) — a leaked ring page is a violation here too
            for p in entry.lane.pages:
                want_refs[p] += 1
                want_pins[p] += 1
        if self._trie is not None:
            stack = list(self._trie.root.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                want_refs[node.rows] += 1
        for p in range(1, n + 1):
            if self._pool.refs(p) != want_refs[p]:
                raise RuntimeError(
                    "page %d holds %d refs but lanes+trie account for "
                    "%d — leaked or double-released"
                    % (p, self._pool.refs(p), want_refs[p]))
            if self._pool._pins[p] != want_pins[p]:
                raise RuntimeError(
                    "page %d holds %d pins but active lanes account "
                    "for %d" % (p, self._pool._pins[p], want_pins[p]))
        return {"paged": True, "free_pages": self._pool.free_pages,
                "used_pages": self._pool.used_pages,
                "pinned_pages": self._pool.pinned_pages}

    # ------------------------------------------------------------------ worker
    def _admit(self):   # hot-path
        """Move queued prompts into free slots.  Feature-off requests
        (and chunked-ineligible ones) prefill whole at a power-of-two
        bucket as before; with ``prefill_chunk`` the lane only LOOKS UP
        the prefix cache and installs its hits here — compute chunks run
        one per tick, interleaved with decode (no head-of-line block).
        Paged mode additionally RESERVES the lane's worst-case pages;
        when the pool cannot cover them the request goes BACK to the
        queue head (FIFO — retried next tick as lanes free pages, shed
        at its deadline) instead of wedging or being skipped."""
        if self._peek_swap() is not None:
            # a finish-on-old swap is quiescing: admitting now would
            # extend old-weights serving indefinitely — the queue
            # waits the (bounded) remaining lane ticks instead
            return
        self._pool_blocked = False
        while self._free:
            with self._cond:
                req = self._queue.popleft() if self._queue else None
                if req is not None:
                    self._queued_tokens -= req.true_len
                    self._queued_pages -= req.pages
                self.metrics.set_gauge("queue_depth", len(self._queue))
                self.metrics.set_gauge("queue_tokens",
                                       self._queued_tokens)
                if self._paged:
                    self.metrics.set_gauge("queue_pages",
                                           self._queued_pages)
            if req is None:
                return
            if req.cancelled:            # raced _cancel's dequeue
                self._trace_queue_end(req, "cancelled")
                req.future.cancel()
                continue
            if time.monotonic() > req.deadline:
                self.metrics.record_shed()
                self._trace_queue_end(req, "shed")
                req.future.set_exception(DeadlineExceeded(
                    "prompt shed after %.3fs in queue" % (
                        time.monotonic() - req.t_enq)))
                continue
            slot = self._free.pop()
            C = self.prefill_chunk
            if self._paged:
                if not self._admit_paged(slot, req):
                    # pool pressure: back to the HEAD (order preserved;
                    # deadline still sheds it) and stop admitting
                    self._free.append(slot)
                    self._pool_blocked = True
                    with self._cond:
                        self._queue.appendleft(req)
                        self._queued_tokens += req.true_len
                        self._queued_pages += req.pages
                        self.metrics.set_gauge("queue_depth",
                                               len(self._queue))
                        self.metrics.set_gauge("queue_tokens",
                                               self._queued_tokens)
                        self.metrics.set_gauge("queue_pages",
                                               self._queued_pages)
                    return
                continue
            if C and ((req.true_len - 1) // C + 1) * C <= self.max_len:
                self._admit_chunked(slot, req)
                continue
            bucket = prompt_bucket(req.true_len, self.max_len)
            prompt = req.prompt
            if bucket > req.true_len:
                prompt = numpy.pad(prompt,
                                   (0, bucket - req.true_len))
            self._trace_admitted(req)
            t0p = time.monotonic()
            try:
                self._fault("engine.prefill")
                tok, rows = self._prefill_jit(
                    self.params,
                    xfer.to_device(prompt[None], numpy.int32),
                    xfer.to_device(req.true_len, numpy.int32),
                    *self._seed_args(req.seed))
                self._caches = self._install_jit(
                    self._caches, rows,
                    xfer.to_device(slot, numpy.int32))
                self._tfence(self._caches, req.trace is not None)
            except Exception as e:   # noqa: BLE001 — fails THIS request
                # a prefill fault (bad bucket compile, device error)
                # must fail its own request, not wedge the engine
                self.metrics.record_error()
                self.warning("prefill failed: %s", e)
                if req.trace is not None:
                    req.trace.tracer.add(
                        req.trace, "prefill", "prefill", t0p,
                        time.monotonic(),
                        attrs={"bucket": bucket, "error": str(e)})
                self._free.append(slot)
                if not req.future.cancelled():
                    req.future.set_exception(e)
                continue
            self.metrics.record_queue_wait(
                time.monotonic() - req.t_enq)
            self.metrics.inc("prefill_tokens", req.true_len)
            if req.trace is not None:
                req.trace.tracer.add(
                    req.trace, "prefill", "prefill", t0p,
                    time.monotonic(),
                    attrs={"bucket": bucket,
                           "backend": self._backend})
            lane = _Slot(req)
            self._lanes[slot] = lane
            self._emit_first(slot, lane, int(xfer.to_host(tok)))

    def _admit_chunked(self, slot, req):   # hot-path
        """Chunked admission: match the prefix cache (full chunks only,
        never the chunk holding the last prompt token — the tail must
        run to produce the first token's logits), COPY hits into the
        lane's cache rows, and queue the rest as per-tick chunk work."""
        C = self.prefill_chunk
        n_full = (req.true_len - 1) // C
        self._trace_admitted(req)
        lane = _Slot(req)
        matched = 0
        if self._trie is not None:
            keys = [tuple(int(t) for t in req.prompt[i * C:(i + 1) * C])
                    for i in range(n_full)]
            nodes = self._trie.match(keys)
            lane.pinned.extend(nodes)
            lane.cursor = nodes[-1] if nodes else self._trie.root
            try:
                for i, node in enumerate(nodes):
                    self._caches = self._chunk_install_jit(
                        self._caches, node.rows,
                        xfer.to_device(slot, numpy.int32),
                        xfer.to_device(i * C, numpy.int32))
            except Exception as e:   # noqa: BLE001 — fails THIS request
                self.metrics.record_error()
                self.warning("prefix-cache install failed: %s", e)
                self._teardown_slot(slot, lane, e)
                return
            matched = len(nodes)
            self.metrics.inc("prefix_hit_chunks", matched)
            self.metrics.inc("prefix_hit_tokens", matched * C)
            # every contiguous hit is a device ROW COPY install — the
            # cost the paged layout's page references eliminate
            self.metrics.inc("kv_row_copies", matched * C)
            self.metrics.set_gauge("prefix_cache_chunks",
                                   self._trie.size)
            if matched and req.trace is not None:
                req.trace.tracer.instant(
                    req.trace, "prefix.hit", cat="prefill",
                    attrs={"chunks": matched, "tokens": matched * C})
        for i in range(matched, n_full):
            lane.pending.append((req.prompt[i * C:(i + 1) * C], i * C,
                                 False))
        tail = req.prompt[n_full * C:]
        if len(tail) < C:
            tail = numpy.pad(tail, (0, C - len(tail)))
        lane.pending.append((tail, n_full * C, True))
        self.metrics.record_queue_wait(time.monotonic() - req.t_enq)
        self._lanes[slot] = lane
        # park the step position at the chunk frontier: the vmapped
        # decode dispatch steps EVERY slot, and a prefilling lane's
        # garbage write must land where its own next chunk (<= C wide,
        # and spec_k + 1 <= C) overwrites before anything attends it
        self._pos[slot] = lane.pending[0][1]

    # -------------------------------------------------------------- paged mode
    def _admit_paged(self, slot, req):   # hot-path
        """Paged admission: reserve the lane's WORST-CASE page span up
        front (no mid-decode allocation, so decode can never deadlock
        on pages), with prefix-cache hits substituting page REFERENCES
        (ref-count bump, no device work at all) for fresh pages.
        Returns False — nothing committed — when the pool cannot cover
        the reservation even after pressing the prefix cache."""
        C = self.prefill_chunk
        n_full = (req.true_len - 1) // C
        lane = _Slot(req)
        nodes = []
        if self._trie is not None:
            keys = [tuple(int(t) for t in req.prompt[i * C:(i + 1) * C])
                    for i in range(n_full)]
            nodes = self._trie.match(keys)
        fresh = self._alloc_pages(req.pages - len(nodes))
        if fresh is None:
            if nodes:            # nothing committed — undo the pins
                self._trie.release(nodes)
            return False
        lane.pinned.extend(nodes)
        lane.cursor = (nodes[-1] if nodes else
                       self._trie.root if self._trie is not None
                       else None)
        for node in nodes:
            self._pool.retain(node.rows)     # the lane's reference
            self._pool.pin(node.rows)
            lane.pages.append(node.rows)
        for p in fresh:
            self._pool.pin(p)
        lane.pages.extend(fresh)
        self._page_tables[slot, :len(lane.pages)] = lane.pages
        self._page_tables[slot, len(lane.pages):] = KVPagePool.SCRATCH
        if nodes:
            self.metrics.inc("prefix_hit_chunks", len(nodes))
            self.metrics.inc("prefix_hit_tokens", len(nodes) * C)
            self.metrics.inc("kv_pages_referenced", len(nodes))
            self.metrics.set_gauge("prefix_cache_chunks",
                                   self._trie.size)
        for i in range(len(nodes), n_full):
            lane.pending.append((req.prompt[i * C:(i + 1) * C], i * C,
                                 False))
        tail = req.prompt[n_full * C:]
        if len(tail) < C:
            tail = numpy.pad(tail, (0, C - len(tail)))
        lane.pending.append((tail, n_full * C, True))
        self.metrics.record_queue_wait(time.monotonic() - req.t_enq)
        self._trace_admitted(req)
        if nodes and req.trace is not None:
            req.trace.tracer.instant(
                req.trace, "prefix.hit", cat="prefill",
                attrs={"chunks": len(nodes),
                       "tokens": len(nodes) * C, "paged": True})
        self._lanes[slot] = lane
        self._pos[slot] = lane.pending[0][1]
        self._update_pool_gauges()
        return True

    def _alloc_pages(self, n):
        """``n`` pages from the pool, pressing the prefix cache to drop
        LRU unpinned entries (each eviction releases its page) until
        the allocation fits or nothing more can be evicted.  Returns
        the page list or None; never blocks."""
        if n <= 0:
            return []
        pages = self._pool.alloc(n)
        if pages is None and self._trie is not None:
            # each eviction frees at most ONE page — when even a full
            # flush cannot cover the deficit, keep the cache warm (the
            # request is only ever placed by lanes finishing anyway)
            if self._pool.free_pages + self._trie.evictable() < n:
                return None
            while pages is None and self._trie.evict_one():
                self.metrics.set_gauge("prefix_cache_chunks",
                                       self._trie.size)
                pages = self._pool.alloc(n)
        return pages

    def _cow_guard(self, slot, lane, lo, hi):   # hot-path
        """COPY-ON-WRITE: before a device write covering linear
        positions [lo, hi), replace any SHARED page in that range with
        a private copy (one page-copy dispatch) so the other referents
        — sibling lanes, the prefix cache — keep their rows
        bit-identical.  Structurally rare (shared pages are full prompt
        chunks; appends land past the prompt), kept as the safety net
        that makes sharing unconditionally sound.  Raises on pool
        exhaustion — the caller fails THIS lane, never wedges.

        ``hi`` is clamped to the lane's reservation: a megastep quotes
        its WORST-CASE span (K iterations all advancing), but a lane's
        real writes never pass its reserved pages (the program freezes
        an exhausted lane and masks its writes to scratch), so pages
        past the reservation need no copy — and indexing them would be
        out of range."""
        P = self.prefill_chunk
        hi = min(hi, len(lane.pages) * P)
        if hi <= lo:
            return
        for j in range(lo // P, (hi - 1) // P + 1):
            p = lane.pages[j]
            if not self._pool.shared(p):
                continue
            fresh = self._alloc_pages(1)
            if fresh is None:
                raise Overloaded()
            q = fresh[0]
            t0c = time.monotonic()
            try:
                self._fault("engine.cow")
                self._kv_pools = self._page_copy_jit(
                    self._kv_pools, xfer.to_device(p, numpy.int32),
                    xfer.to_device(q, numpy.int32))
                self._tfence(self._kv_pools,
                             lane.request.trace is not None)
            except Exception:
                # nobody owns q yet (not in lane.pages) — hand it back
                # or a faulting device shrinks the pool for good
                self._pool.release(q)
                raise
            if lane.request.trace is not None:
                lane.request.trace.tracer.add(
                    lane.request.trace, "cow.copy", "kv", t0c,
                    time.monotonic(),
                    attrs={"page": p, "bucket": self.prefill_chunk,
                           "backend": self._backend})
            self._pool.pin(q)
            self._pool.unpin(p)
            self._pool.release(p)
            lane.pages[j] = q
            self._page_tables[slot, j] = q
            self.metrics.inc("kv_cow_copies")
            self._update_pool_gauges()

    def _cow_guard_active(self, active, span):
        """:meth:`_cow_guard` over every active lane's next
        ``span``-position write, BEFORE the batched dispatch: a lane
        whose copy cannot be made (pool exhausted on the safety-net
        path) is torn down ALONE — its siblings keep decoding, per the
        engine's fault-isolation discipline.  Returns the surviving
        active list (a torn-down lane's table row parks on scratch, so
        the batched step stays safe to run)."""
        alive = []
        for slot in active:
            lane = self._lanes[slot]
            try:
                self._cow_guard(slot, lane, int(self._pos[slot]),
                                int(self._pos[slot]) + span)
            except Exception as e:   # noqa: BLE001 — fails THIS lane
                self.metrics.record_error()
                self.warning("copy-on-write failed: %s", e)
                self._teardown_slot(slot, lane, e)
                continue
            alive.append(slot)
        return alive

    def _update_pool_gauges(self):
        self.metrics.set_gauge("kv_pages_free", self._pool.free_pages)
        self.metrics.set_gauge("kv_pages_pinned",
                               self._pool.pinned_pages)

    def _live_width(self, span, floor=0):
        """Ladder-bucketed page-table width for a decode/verify step
        writing ``span`` positions per lane: the smallest power-of-two
        (capped at max_pages) covering EVERY slot's frontier —
        ``_pos`` includes prefilling lanes' parked frontiers and the
        inactive lanes' 0, so the batched step's garbage writes always
        land inside the sliced table (take_along_axis would otherwise
        CLAMP an out-of-range page lookup onto a live page).  ``floor``
        raises the covered frontier past the slots' own — the while
        megastep passes its published standby lanes' positions so a
        ring entry armed mid-loop writes inside the sliced width
        too."""
        need = -(-(max(int(self._pos.max()), floor) + span)
                 // self.prefill_chunk)
        for w in self._width_ladder:
            if w >= need:
                return w
        return self._max_pages

    def _note_attn_dispatch(self):
        """Per-dispatch kernel accounting (ISSUE 7): which path the
        engine's attention actually took.  Only metered when the caller
        ASKED for kernels — an untouched engine carries no new
        counters."""
        if self.attn_kernel:
            self.metrics.inc("attn_kernel_dispatches"
                             if self._kernel_active
                             else "attn_kernel_fallbacks")

    def kv_bytes_resident(self):
        """Device bytes held for KV storage — the pool (paged) or the
        contiguous slot caches; what the bench reports as footprint."""
        arrs = [a for pair in (self._kv_pools if self._paged
                               else self._caches) for a in pair]
        return sum(a.size * a.dtype.itemsize for a in arrs)

    def _advance_prefill(self, slot):   # hot-path
        """Run ONE pending prompt chunk for this lane (a tick's worth of
        prefill — decode lanes step in between, so a long prompt never
        head-of-line-blocks them).  Computed full chunks feed the prefix
        cache; the tail chunk yields the first generated token."""
        lane = self._lanes[slot]
        req = lane.request
        if req.cancelled:
            # withdrawn (generate() sibling cancellation) mid-prefill:
            # free the slot now instead of finishing the prompt for a
            # result nobody will read
            self._teardown_slot(slot, lane)
            return
        if self._paged:
            self._advance_prefill_paged(slot, lane, req)
            return
        tokens, start, is_tail = lane.pending.pop(0)
        if not is_tail and self._trie is not None \
                and lane.cursor is not None:
            # LATE HIT: a sibling lane prefilling the same prompt may
            # have inserted this very chunk since admission — install
            # its rows instead of recomputing, so concurrent
            # shared-prefix arrivals converge on ONE prefill
            node = self._trie.lookup_child(
                lane.cursor, tuple(int(t) for t in tokens))
            if node is not None:
                try:
                    self._caches = self._chunk_install_jit(
                        self._caches, node.rows,
                        xfer.to_device(slot, numpy.int32),
                        xfer.to_device(start, numpy.int32))
                except Exception as e:   # noqa: BLE001 — this request
                    self._trie.release([node])
                    self.metrics.record_error()
                    self.warning("prefix-cache install failed: %s", e)
                    self._teardown_slot(slot, lane, e)
                    return
                lane.pinned.append(node)
                lane.cursor = node
                self.metrics.inc("prefix_hit_chunks")
                self.metrics.inc("prefix_hit_tokens", len(tokens))
                self.metrics.inc("kv_row_copies", len(tokens))
                if req.trace is not None:
                    req.trace.tracer.instant(
                        req.trace, "prefix.hit", cat="prefill",
                        attrs={"late": True, "start": start})
                self._pos[slot] = lane.pending[0][1]
                return
        last_idx = (req.true_len - 1 - start) if is_tail else 0
        t0 = time.monotonic()
        try:
            self._fault("engine.chunk")
            self._caches, tok = self._chunk_jit(
                self.params, self._caches,
                xfer.to_device(tokens, numpy.int32),
                xfer.to_device(slot, numpy.int32),
                xfer.to_device(start, numpy.int32),
                xfer.to_device(last_idx, numpy.int32),
                *self._seed_args(req.seed))
            if not is_tail and self._trie is not None \
                    and lane.cursor is not None:
                rows = self._chunk_extract_jit(
                    self._caches, xfer.to_device(slot, numpy.int32),
                    xfer.to_device(start, numpy.int32))
                node = self._trie.insert(
                    lane.cursor, tuple(int(t) for t in tokens), rows)
                if node is not None:
                    lane.pinned.append(node)
                lane.cursor = node
                self.metrics.set_gauge("prefix_cache_chunks",
                                       self._trie.size)
            self._tfence(self._caches, req.trace is not None)
        except Exception as e:   # noqa: BLE001 — fails THIS request
            self.metrics.record_error()
            self.warning("chunk prefill failed: %s", e)
            if req.trace is not None:
                # the FAILED dispatch is part of the timeline — the
                # flight recorder must show where the request died (no
                # backend attr: failed spans stay out of the ledger)
                req.trace.tracer.add(
                    req.trace, "prefill.chunk", "prefill", t0,
                    time.monotonic(),
                    attrs={"start": start, "error": str(e)})
            self._teardown_slot(slot, lane, e)
            return
        self.metrics.inc("prefill_dispatches")
        self._note_attn_dispatch()
        self.metrics.inc("prefill_tokens",
                         (req.true_len - start) if is_tail
                         else len(tokens))
        # lint: allow(host-sync): enqueue-time EWMA by design; device wall rides traced spans (_tfence)
        self.metrics.record_decode_step(time.monotonic() - t0)
        if req.trace is not None:
            req.trace.tracer.add(
                req.trace, "prefill.chunk", "prefill", t0,
                time.monotonic(),
                attrs={"start": start, "tail": is_tail,
                       "bucket": self.prefill_chunk,
                       "backend": self._backend})
        if is_tail:
            self._emit_first(slot, lane, int(xfer.to_host(tok)))
        else:
            self._pos[slot] = lane.pending[0][1]

    def _advance_prefill_paged(self, slot, lane, req):   # hot-path
        """One pending prompt chunk, paged: a LATE HIT swaps the lane's
        reserved page for a REFERENCE to the sibling's page (release
        one, retain the other — still zero device work); a computed
        full chunk SHARES the lane's own page with the trie (retain —
        the insert itself copies nothing)."""
        C = self.prefill_chunk
        tokens, start, is_tail = lane.pending.pop(0)
        page_idx = start // C
        if not is_tail and self._trie is not None \
                and lane.cursor is not None:
            node = self._trie.lookup_child(
                lane.cursor, tuple(int(t) for t in tokens))
            if node is not None:
                # late hit: drop the page reserved for this chunk and
                # reference the already-computed one instead
                own = lane.pages[page_idx]
                self._pool.unpin(own)
                self._pool.release(own)
                self._pool.retain(node.rows)
                self._pool.pin(node.rows)
                lane.pages[page_idx] = node.rows
                self._page_tables[slot, page_idx] = node.rows
                lane.pinned.append(node)
                lane.cursor = node
                self.metrics.inc("prefix_hit_chunks")
                self.metrics.inc("prefix_hit_tokens", len(tokens))
                self.metrics.inc("kv_pages_referenced")
                if req.trace is not None:
                    req.trace.tracer.instant(
                        req.trace, "prefix.hit", cat="prefill",
                        attrs={"late": True, "start": start,
                               "paged": True})
                self._update_pool_gauges()
                self._pos[slot] = lane.pending[0][1]
                return
        last_idx = (req.true_len - 1 - start) if is_tail else 0
        t0 = time.monotonic()
        try:
            self._fault("engine.chunk")
            self._cow_guard(slot, lane, start, start + C)
            self._kv_pools, tok = self._chunk_jit(
                self.params, self._kv_pools,
                xfer.to_device(self._page_tables[slot]),
                xfer.to_device(tokens, numpy.int32),
                xfer.to_device(start, numpy.int32),
                xfer.to_device(last_idx, numpy.int32),
                *self._seed_args(req.seed))
            if not is_tail and self._trie is not None \
                    and lane.cursor is not None:
                page = lane.pages[page_idx]
                node = self._trie.insert(
                    lane.cursor, tuple(int(t) for t in tokens), page)
                if node is not None:
                    lane.pinned.append(node)
                    if node.rows == page:
                        # fresh entry: the trie now references the
                        # lane's own page (released on trie eviction)
                        self._pool.retain(page)
                lane.cursor = node
                self.metrics.set_gauge("prefix_cache_chunks",
                                       self._trie.size)
                self._update_pool_gauges()
            self._tfence(self._kv_pools, req.trace is not None)
        except Exception as e:   # noqa: BLE001 — fails THIS request
            self.metrics.record_error()
            self.warning("paged chunk prefill failed: %s", e)
            if req.trace is not None:
                req.trace.tracer.add(
                    req.trace, "prefill.chunk", "prefill", t0,
                    time.monotonic(),
                    attrs={"start": start, "paged": True,
                           "error": str(e)})
            self._teardown_slot(slot, lane, e)
            return
        self.metrics.inc("prefill_dispatches")
        self._note_attn_dispatch()
        self.metrics.inc("prefill_tokens",
                         (req.true_len - start) if is_tail
                         else len(tokens))
        # lint: allow(host-sync): enqueue-time EWMA by design; device wall rides traced spans (_tfence)
        self.metrics.record_decode_step(time.monotonic() - t0)
        if req.trace is not None:
            req.trace.tracer.add(
                req.trace, "prefill.chunk", "prefill", t0,
                time.monotonic(),
                attrs={"start": start, "tail": is_tail,
                       "bucket": self.prefill_chunk, "paged": True,
                       "backend": self._backend})
        if is_tail:
            self._emit_first(slot, lane, int(xfer.to_host(tok)))
        else:
            self._pos[slot] = lane.pending[0][1]

    def _emit_first(self, slot, lane, tok):
        """First generated token (prefill just finished): the lane
        becomes a decode lane (or finishes outright at n_new=1)."""
        req = lane.request
        lane.emitted.append(tok)
        lane.remaining -= 1
        self.metrics.inc("tokens_out")
        self.metrics.record_ttft(time.monotonic() - req.t_enq)
        self._pos[slot] = req.true_len
        self._last[slot] = tok
        self._lanes[slot] = lane
        if lane.remaining == 0 or req.cancelled:
            self._finish(slot)

    def _release_lane(self, lane):
        if self._trie is not None and lane.pinned:
            self._trie.release(lane.pinned)
            lane.pinned = []
        if self._paged and lane.pages:
            # ref-count release on lane finish: owned pages return to
            # the free list; shared (trie/sibling-referenced) pages
            # just lose this lane's reference and survive
            for p in lane.pages:
                self._pool.unpin(p)
                self._pool.release(p)
            lane.pages = []
            self._update_pool_gauges()

    def _vacate_slot(self, slot, lane):
        """Release a lane's trie pins/pages and free its slot WITHOUT
        touching the request future — finish, teardown and the swap
        requeue all funnel here so none can forget a step.  The step
        position parks at 0 (a free slot's garbage writes land where
        the next admission overwrites them)."""
        self._release_lane(lane)
        self._lanes[slot] = None
        if slot not in self._free:
            self._free.append(slot)
        self._pos[slot] = 0
        self._last[slot] = 0
        if self._paged:
            self._page_tables[slot, :] = KVPagePool.SCRATCH

    def _teardown_slot(self, slot, lane, exc=None):
        """THE failure/cancellation teardown: vacate the slot and fail
        — or, when ``exc`` is None, cancel — the request's future."""
        self._vacate_slot(slot, lane)
        fut = lane.request.future
        if exc is None:
            fut.cancel()
        elif not fut.cancelled():
            fut.set_exception(exc)

    def _finish(self, slot):
        lane = self._lanes[slot]
        self._vacate_slot(slot, lane)
        fut = lane.request.future
        if not fut.cancelled():          # withdrawn mid-decode
            # stamped with the generation that produced these tokens —
            # the mixed-fleet attribution a rolling deploy needs
            fut.version = self.weights_version
            fut.set_result(numpy.asarray(lane.emitted, numpy.int32))

    def _fail_active(self, active, exc):
        """A step/verify fault poisons every in-flight decode lane; fail
        them to their clients and keep serving — never wedge with
        futures that no one will ever resolve."""
        self.metrics.record_error()
        self.warning("decode step failed: %s", exc)
        for slot in active:
            self._teardown_slot(slot, self._lanes[slot], exc)

    def _step_plain(self, active):   # hot-path
        """ONE dispatch advances every active lane by one token;
        inactive lanes step too (their writes land at a frozen position
        that the next prefill/chunk overwrites before attending — see
        the module docstring), so the step program never respecializes
        on the active set."""
        if self._paged:
            active = self._cow_guard_active(active, 1)
            if not active:
                return
        w = None
        tctxs = ()
        if self._tracer is not None:
            # only the SAMPLED lanes carry a context — an all-None
            # batch records nothing and (sample:P) skips the fence
            tctxs = [self._lanes[s].request.trace for s in active]
        t0 = time.monotonic()
        try:
            self._fault("engine.step")
            if self._paged:
                w = self._live_width(1)
                self._kv_pools, toks = self._step_jit(
                    self.params, self._kv_pools,
                    xfer.to_device(self._page_tables[:, :w]),
                    xfer.to_device(self._last),
                    xfer.to_device(self._pos), *self._seed_vec())
            else:
                self._caches, toks = self._step_jit(
                    self.params, self._caches,
                    xfer.to_device(self._last),
                    xfer.to_device(self._pos), *self._seed_vec())
            toks = xfer.to_host(toks)
            self._tfence(self._kv_pools if self._paged
                         else self._caches,
                         any(c is not None for c in tctxs))
        except Exception as e:   # noqa: BLE001 — fails the lanes
            if self._tracer is not None:
                self._tracer.add_many(
                    tctxs, "decode.step", "decode", t0,
                    time.monotonic(),
                    attrs={"batch": len(active), "error": str(e)})
            self._fail_active(active, e)
            return
        self.metrics.record_dispatch(len(active))
        self.metrics.record_decode_step(time.monotonic() - t0)
        self.metrics.inc("decode_dispatches")
        self._note_attn_dispatch()
        if self._tracer is not None:
            self._tracer.add_many(
                tctxs, "decode.step", "decode", t0, time.monotonic(),
                attrs={"batch": len(active),
                       "bucket": w if w is not None else self.slots,
                       "backend": self._backend})
        for slot in active:
            lane = self._lanes[slot]
            lane.emitted.append(int(toks[slot]))
            lane.remaining -= 1
            self.metrics.inc("tokens_out")
            self._pos[slot] += 1
            self._last[slot] = int(toks[slot])
            if lane.remaining == 0 or lane.request.cancelled:
                self._finish(slot)

    def _step_speculative(self, active):   # hot-path
        """ONE verify dispatch advances every active lane by 1..k+1
        tokens: each lane feeds [last, draft…] (draft = prompt-lookup
        n-gram continuation, zeros when none) and accepts the longest
        draft prefix matching the verifier's own greedy argmax, plus
        the correction/bonus token after it — bit-identical to plain
        greedy decode by construction, at < 1 dispatch/token whenever
        drafts hit."""
        k = self.spec_k
        if self._paged:
            active = self._cow_guard_active(active, k + 1)
            if not active:
                return
        toks_in = numpy.zeros((self.slots, k + 1), numpy.int32)
        drafts = [None] * self.slots
        real_lens = [0] * self.slots
        for slot in active:
            lane = self._lanes[slot]
            toks_in[slot, 0] = self._last[slot]
            history = numpy.concatenate(
                [lane.request.prompt,
                 numpy.asarray(lane.emitted, numpy.int32)])
            draft = propose_draft(history, k, self.spec_ngram)
            if draft is not None:
                # zero-pad to the program's fixed k (padding is free:
                # a pad only "accepts" when it IS the greedy token) but
                # METER only the real continuation — acceptance rates
                # must not be diluted by padding nor inflated by
                # coincidental token-0 matches
                padded = numpy.zeros(k, numpy.int32)
                padded[:len(draft)] = draft
                toks_in[slot, 1:] = padded
                drafts[slot] = padded
                real_lens[slot] = len(draft)
                self.metrics.inc("draft_tokens", len(draft))
        w = None
        tctxs = ()
        if self._tracer is not None:
            tctxs = [self._lanes[s].request.trace for s in active]
        t0 = time.monotonic()
        try:
            self._fault("engine.verify")
            if self._paged:
                w = self._live_width(k + 1)
                self._kv_pools, out = self._verify_jit(
                    self.params, self._kv_pools,
                    xfer.to_device(self._page_tables[:, :w]),
                    xfer.to_device(toks_in), xfer.to_device(self._pos),
                    *self._seed_vec())
            else:
                self._caches, out = self._verify_jit(
                    self.params, self._caches, xfer.to_device(toks_in),
                    xfer.to_device(self._pos), *self._seed_vec())
            out = xfer.to_host(out)
            self._tfence(self._kv_pools if self._paged
                         else self._caches,
                         any(c is not None for c in tctxs))
        except Exception as e:   # noqa: BLE001 — fails the lanes
            if self._tracer is not None:
                self._tracer.add_many(
                    tctxs, "decode.verify", "decode", t0,
                    time.monotonic(),
                    attrs={"batch": len(active), "error": str(e)})
            self._fail_active(active, e)
            return
        self.metrics.record_dispatch(len(active))
        self.metrics.record_decode_step(time.monotonic() - t0)
        self.metrics.inc("decode_dispatches")
        self._note_attn_dispatch()
        if self._tracer is not None:
            self._tracer.add_many(
                tctxs, "decode.verify", "decode", t0, time.monotonic(),
                attrs={"batch": len(active), "k": k,
                       "bucket": w if w is not None else self.slots,
                       "backend": self._backend})
        for slot in active:
            lane = self._lanes[slot]
            draft = drafts[slot]
            accepted = 0
            if draft is not None:
                while accepted < k and \
                        out[slot, accepted] == draft[accepted]:
                    accepted += 1
                self.metrics.inc("draft_accepted",
                                 min(accepted, real_lens[slot]))
            # accepted drafts ARE the greedy tokens (they matched the
            # verifier's argmax); out[accepted] is the greedy token
            # after them (correction on mismatch, bonus on full hit)
            emit = [int(t) for t in
                    (draft[:accepted].tolist() if draft is not None
                     else [])]
            emit.append(int(out[slot, accepted]))
            take = min(len(emit), lane.remaining)
            lane.emitted.extend(emit[:take])
            lane.remaining -= take
            self.metrics.inc("tokens_out", take)
            self._pos[slot] += accepted + 1
            self._last[slot] = int(out[slot, accepted])
            if lane.remaining == 0 or lane.request.cancelled:
                self._finish(slot)

    def _step_megastep(self, active):   # hot-path
        """ONE fused dispatch advances every active lane by up to K
        tokens (up to K·(spec_k+1) speculative): the ``lax.scan``
        program from :meth:`_make_megastep_body`.  The host's only
        per-token work is reading the returned emitted-token buffer at
        the BOUNDARY — admission, completion, deadline shedding, swap
        application and tracing all happen once per megastep, not per
        token, which is the whole point (ISSUE 13)."""
        K, k = self.megastep, self.spec_k
        # worst-case per-lane span this dispatch can write (the cow
        # guard and the live-width slice must cover every real write;
        # _cow_guard clamps to each lane's reservation, _live_width to
        # max_pages)
        span = K * (k + 1) + k if k else K
        if self._paged:
            active = self._cow_guard_active(active, span)
            if not active:
                return
        left = numpy.zeros(self.slots, numpy.int32)
        for slot in active:
            left[slot] = self._lanes[slot].remaining
        extra = ()
        if k:
            # the in-graph proposer's token history: prompt + emitted
            # so far per lane, rebuilt from host truth each boundary
            hist = numpy.zeros((self.slots, self.max_len), numpy.int32)
            hlen = numpy.zeros(self.slots, numpy.int32)
            for slot in active:
                lane = self._lanes[slot]
                row = numpy.concatenate(
                    [lane.request.prompt,
                     numpy.asarray(lane.emitted, numpy.int32)])
                hist[slot, :len(row)] = row
                hlen[slot] = len(row)
            extra = (xfer.to_device(hist), xfer.to_device(hlen))
        extra = extra + self._seed_vec()
        w = None
        tctxs = ()
        if self._tracer is not None:
            tctxs = [self._lanes[s].request.trace for s in active]
        t0 = time.monotonic()
        try:
            self._fault("engine.step")
            if self._paged:
                w = self._live_width(span)
                out = self._megastep_jit(
                    self.params, self._kv_pools,
                    xfer.to_device(self._page_tables[:, :w]),
                    xfer.to_device(self._last),
                    xfer.to_device(self._pos),
                    xfer.to_device(left), *extra)
                self._kv_pools = out[0]
            else:
                out = self._megastep_jit(
                    self.params, self._caches,
                    xfer.to_device(self._last),
                    xfer.to_device(self._pos),
                    xfer.to_device(left), *extra)
                self._caches = out[0]
            last, pos, emitted = xfer.to_host((out[1], out[2], out[3]))
            accs = xfer.to_host(out[4]) if k else None
            self._tfence(self._kv_pools if self._paged
                         else self._caches,
                         any(c is not None for c in tctxs))
        except Exception as e:   # noqa: BLE001 — fails the lanes
            if self._tracer is not None:
                self._tracer.add_many(
                    tctxs, "decode.megastep", "decode", t0,
                    time.monotonic(),
                    attrs={"batch": len(active), "K": K,
                           "error": str(e)})
            self._fail_active(active, e)
            return
        t1 = time.monotonic()
        # sync the host frontiers from the program's final carry
        # (frozen lanes returned their entry values, so this is a
        # wholesale assignment)
        self._pos = numpy.array(pos, numpy.int32)
        self._last = numpy.array(last, numpy.int32)
        lane_tokens = {}
        wasted = 0
        for slot in active:
            lane = self._lanes[slot]
            rows = (emitted[:, slot, :] if k
                    else emitted[:, slot][:, None])        # (K, c)
            toks = rows[rows >= 0]       # iteration-major real tokens
            wasted += int((rows[:, 0] < 0).sum())
            lane.emitted.extend(int(t) for t in toks)
            lane.remaining -= len(toks)
            lane_tokens[slot] = int(len(toks))
            self.metrics.inc("tokens_out", len(toks))
        if accs is not None:
            # in-graph drafts are always k wide (padded), so the
            # megastep meters k proposed per live iteration — the
            # acceptance-rate column reads conservatively vs the host
            # proposer's real-length metering (documented in USAGE.md)
            live_iters = int((accs >= 0).sum())
            self.metrics.inc("draft_tokens", k * live_iters)
            self.metrics.inc("draft_accepted",
                             int(numpy.clip(accs, 0, k).sum()))
        total = sum(lane_tokens.values())
        self.metrics.record_dispatch(len(active))
        self.metrics.record_decode_step(t1 - t0)
        self.metrics.inc("decode_dispatches")
        self.metrics.record_megastep(K, len(active), total, wasted)
        self._note_attn_dispatch()
        if self._tracer is not None:
            # ONE decode.megastep span per dispatch, shared did so the
            # cost ledger counts the fused program once — never the
            # folded per-token work; per-lane tokens ride each copy's
            # own attrs (ISSUE 12 stays truthful)
            self._tracer.add_many(
                tctxs, "decode.megastep", "decode", t0, t1,
                attrs={"batch": len(active), "K": K, "tokens": total,
                       "bucket": "%sxK%d" % (w if w is not None
                                             else self.slots, K),
                       "backend": self._backend},
                each_attrs=[{"lane_tokens": lane_tokens[s]}
                            for s in active])
        for slot in active:
            lane = self._lanes[slot]
            if lane.remaining == 0 or lane.request.cancelled:
                self._finish(slot)

    # ---------------------------------------------- ISSUE 19: while megastep
    def _ring_args(self, pub, w):
        """Device arguments publishing ``pub`` (the READY standby
        entries) into the while-megastep carry, zero-padded to the
        fixed ring size R — the program family depends on R and the
        page-table width, never on occupancy (count=0 simply arms
        nothing).  Padding table rows park on SCRATCH like a free
        slot's."""
        R = self.refill_ring
        tabs = numpy.full((R, w), KVPagePool.SCRATCH, numpy.int32)
        last = numpy.zeros(R, numpy.int32)
        pos = numpy.zeros(R, numpy.int32)
        left = numpy.zeros(R, numpy.int32)
        if self.spec_k:
            hist = numpy.zeros((R, self.max_len), numpy.int32)
            hlen = numpy.zeros(R, numpy.int32)
        seeds = numpy.zeros(R, numpy.int32)
        for j, entry in enumerate(pub):
            lane = entry.lane
            tabs[j] = entry.table[:w]
            last[j] = entry.last
            pos[j] = entry.pos
            left[j] = lane.remaining
            if self.spec_k:
                row = numpy.concatenate(
                    [lane.request.prompt,
                     numpy.asarray(lane.emitted, numpy.int32)])
                hist[j, :len(row)] = row
                hlen[j] = len(row)
            seeds[j] = lane.request.seed
        args = [xfer.to_device(tabs), xfer.to_device(last),
                xfer.to_device(pos), xfer.to_device(left)]
        if self.spec_k:
            args += [xfer.to_device(hist), xfer.to_device(hlen)]
        if self._sampling:
            args.append(xfer.to_device(seeds))
        args.append(xfer.to_device(len(pub), numpy.int32))
        return args

    def _ring_zero_args(self, w):
        """Empty-ring dispatch arguments at width ``w`` (warmup)."""
        return self._ring_args([], w)

    def _step_while(self, active):   # hot-path
        """ONE early-exit fused dispatch (ISSUE 19): the
        ``lax.while_loop`` megastep advances every active lane until
        ALL are drained — or the K-iteration cap lands — instead of
        burning masked iterations to a fixed-K boundary, and arms
        published standby-ring lanes into slots that drain mid-loop.
        The host's boundary work mirrors :meth:`_step_megastep` plus:
        read back the REALIZED iteration count (the span/ledger and
        waste metering quote it, not the cap), split each slot's
        emitted stream between the outgoing lane and its in-graph
        replacements (sequential by construction: a lane only stops
        emitting when drained, and ring entries arm in ring order),
        resolve replacements that finished inside the loop, and
        install the last unfinished replacement as the slot's lane."""
        K, k = self.megastep, self.spec_k
        span = K * (k + 1) + k if k else K
        if self._paged:
            active = self._cow_guard_active(active, span)
            if not active:
                return
        left = numpy.full(self.slots, -1, numpy.int32)
        for slot in active:
            left[slot] = self._lanes[slot].remaining
        pub = []
        if self.refill_ring:
            # a free slot enters at left=0: rearm-eligible from
            # iteration 0 (a mid-loop drain is just the common case,
            # not a precondition); prefilling slots stay at -1 so the
            # in-graph arm can NEVER clobber a host-side prefill
            for slot in self._free:
                left[slot] = 0
            if self._peek_swap() is None:
                # quiescing swap: entries prefilled on the old weights
                # must not arm now and decode past the apply
                pub = [e for e in self._ring
                       if e.ready and not e.lane.request.cancelled]
        extra = ()
        if k:
            hist = numpy.zeros((self.slots, self.max_len), numpy.int32)
            hlen = numpy.zeros(self.slots, numpy.int32)
            for slot in active:
                lane = self._lanes[slot]
                row = numpy.concatenate(
                    [lane.request.prompt,
                     numpy.asarray(lane.emitted, numpy.int32)])
                hist[slot, :len(row)] = row
                hlen[slot] = len(row)
            extra = (xfer.to_device(hist), xfer.to_device(hlen))
        extra = extra + self._seed_vec()
        w = None
        tctxs = ()
        if self._tracer is not None:
            # standby occupants participate in this dispatch: the span
            # lands in THEIR trace trees too (sound trees under chaos)
            tctxs = [self._lanes[s].request.trace for s in active] \
                + [e.lane.request.trace for e in pub]
        t0 = time.monotonic()
        try:
            self._fault("engine.step")
            if self._paged:
                floor = max([e.pos for e in pub] or [0])
                w = self._live_width(span, floor)
                args = [self.params, self._kv_pools,
                        xfer.to_device(self._page_tables[:, :w]),
                        xfer.to_device(self._last),
                        xfer.to_device(self._pos),
                        xfer.to_device(left)] + list(extra)
                if self.refill_ring:
                    args += self._ring_args(pub, w)
                out = self._whilestep_jit(*args)
                self._kv_pools = out[0]
            else:
                out = self._whilestep_jit(
                    self.params, self._caches,
                    xfer.to_device(self._last),
                    xfer.to_device(self._pos),
                    xfer.to_device(left), *extra)
                self._caches = out[0]
            last, pos, emitted, iters = xfer.to_host(
                (out[1], out[2], out[3], out[4]))
            accs = xfer.to_host(out[5]) if k else None
            assign = (xfer.to_host(out[5 + (1 if k else 0)])
                      if self.refill_ring else None)
            self._tfence(self._kv_pools if self._paged
                         else self._caches,
                         any(c is not None for c in tctxs))
        except Exception as e:   # noqa: BLE001 — fails the lanes
            if self._tracer is not None:
                self._tracer.add_many(
                    tctxs, "decode.megastep", "decode", t0,
                    time.monotonic(),
                    attrs={"batch": len(active) + len(pub), "K": K,
                           "error": str(e)})
            self._fail_active(active, e)
            for entry in pub:
                # a mid-loop fault fails exactly the participants —
                # published ring occupants included, their pages home
                self._fail_standby(entry, e)
            return
        t1 = time.monotonic()
        iters = int(iters)
        self._pos = numpy.array(pos, numpy.int32)
        self._last = numpy.array(last, numpy.int32)
        armed = {}                      # slot -> entries, in arm order
        if assign is not None:
            for j, entry in enumerate(pub):
                s = int(assign[j])
                if s >= 0:
                    armed.setdefault(s, []).append(entry)
                    self._ring.remove(entry)
        participants = sorted(set(active) | set(armed))
        lane_tokens = {}
        wasted = 0
        total = 0
        for slot in participants:
            rows = (emitted[:iters, slot, :] if k
                    else emitted[:iters, slot][:, None])
            toks = [int(t) for t in rows[rows >= 0]]
            wasted += int((rows[:, 0] < 0).sum())
            total += len(toks)
            lane_tokens[slot] = len(toks)
            owners = ([self._lanes[slot]] if slot in active else []) \
                + [e.lane for e in armed.get(slot, ())]
            for lane in owners:
                take = min(lane.remaining, len(toks))
                lane.emitted.extend(toks[:take])
                lane.remaining -= take
                toks = toks[take:]
            self.metrics.inc("tokens_out", lane_tokens[slot])
        if accs is not None:
            live_iters = int((accs[:iters] >= 0).sum())
            self.metrics.inc("draft_tokens", k * live_iters)
            self.metrics.inc("draft_accepted",
                             int(numpy.clip(accs[:iters], 0, k).sum()))
        n_armed = sum(len(v) for v in armed.values())
        if n_armed:
            self.metrics.inc("megastep_refills", n_armed)
        self.metrics.set_gauge("standby_ring_occupancy",
                               len(self._ring))
        self.metrics.record_dispatch(len(participants))
        self.metrics.record_decode_step(t1 - t0)
        self.metrics.inc("decode_dispatches")
        # REALIZED iterations, not the cap: the waste gauge must read
        # what the early exit actually saved
        self.metrics.record_megastep(iters, len(participants), total,
                                     wasted)
        self._note_attn_dispatch()
        if self._tracer is not None:
            self._tracer.add_many(
                tctxs, "decode.megastep", "decode", t0, t1,
                attrs={"batch": len(participants), "K": K,
                       "iters": iters, "tokens": total,
                       "bucket": "%sxK%d" % (w if w is not None
                                             else self.slots, K),
                       "backend": self._backend},
                each_attrs=[{"lane_tokens": lane_tokens.get(s, 0)}
                            for s in active]
                + [{"standby": True} for _ in pub])
        for slot in participants:
            if slot in active:
                lane = self._lanes[slot]
                if lane.remaining == 0 or lane.request.cancelled:
                    self._finish(slot)
            for entry in armed.get(slot, ()):
                lane = entry.lane
                if lane.remaining == 0 or lane.request.cancelled:
                    self._resolve_standby(entry)
                else:
                    # still decoding at the cap: the entry BECOMES the
                    # slot's lane — restore the frontier that
                    # _finish's vacate reset, and the full-width page
                    # table row from the entry's own reservation
                    self._lanes[slot] = lane
                    if slot in self._free:
                        self._free.remove(slot)
                    self._page_tables[slot] = entry.table
                    self._pos[slot] = int(pos[slot])
                    self._last[slot] = int(last[slot])
            if self._lanes[slot] is None:
                # every owner drained: park the freed slot's frontier
                # back at the garbage-write discipline's 0
                self._pos[slot] = 0
                self._last[slot] = 0
                if self._paged:
                    self._page_tables[slot, :] = KVPagePool.SCRATCH

    # --------------------------------------------- ISSUE 19: standby ring
    def _admit_ring(self):   # hot-path
        """Install READY standby lanes into free slots HOST-side: the
        ring's fast path is the in-graph arm, but when a slot frees at
        a boundary (or lanes drained while the ring was still
        prefilling) the entry must not wait for a mid-loop drain that
        can never come."""
        if not self.refill_ring or self._peek_swap() is not None:
            return
        while self._free and self._ring:
            entry = next((e for e in self._ring if e.ready), None)
            if entry is None:
                return
            self._ring.remove(entry)
            lane = entry.lane
            if lane.request.cancelled:
                self._drop_standby(entry)
                continue
            slot = self._free.pop()
            self._lanes[slot] = lane
            self._page_tables[slot] = entry.table
            self._pos[slot] = entry.pos
            self._last[slot] = entry.last
            self.metrics.set_gauge("standby_ring_occupancy",
                                   len(self._ring))

    def _advance_ring(self):   # hot-path
        """One tick of standby-ring work (ISSUE 19): advance ONE
        pending standby prefill chunk, or — when every slot is busy,
        the ring has room and no swap is quiescing — pull the queue
        head into a fresh standby entry.  Pages are reserved
        all-or-nothing exactly like :meth:`_admit_paged`, but with NO
        prefix-cache interaction: a standby page is never shared, so
        the in-graph arm needs no COW guard."""
        if not self.refill_ring:
            return
        for entry in list(self._ring):
            # withdrawn entries give their pages home NOW, not at some
            # future boundary
            if entry.lane.request.cancelled:
                self._drop_standby(entry)
        if self._peek_swap() is not None:
            return
        entry = next((e for e in self._ring if not e.ready), None)
        if entry is not None:
            self._advance_standby_chunk(entry)
            return
        if self._free or len(self._ring) >= self.refill_ring:
            return
        with self._cond:
            req = self._queue.popleft() if self._queue else None
            if req is not None:
                self._queued_tokens -= req.true_len
                self._queued_pages -= req.pages
                self.metrics.set_gauge("queue_depth", len(self._queue))
                self.metrics.set_gauge("queue_tokens",
                                       self._queued_tokens)
                self.metrics.set_gauge("queue_pages",
                                       self._queued_pages)
        if req is None:
            return
        if req.cancelled:
            self._trace_queue_end(req, "cancelled")
            req.future.cancel()
            return
        if time.monotonic() > req.deadline:
            self.metrics.record_shed()
            self._trace_queue_end(req, "shed")
            req.future.set_exception(DeadlineExceeded(
                "prompt shed after %.3fs in queue" % (
                    time.monotonic() - req.t_enq)))
            return
        pages = self._alloc_pages(req.pages)
        if pages is None:
            # pool pressure: back to the HEAD, exactly like _admit
            with self._cond:
                self._queue.appendleft(req)
                self._queued_tokens += req.true_len
                self._queued_pages += req.pages
                self.metrics.set_gauge("queue_depth", len(self._queue))
                self.metrics.set_gauge("queue_tokens",
                                       self._queued_tokens)
                self.metrics.set_gauge("queue_pages",
                                       self._queued_pages)
            return
        lane = _Slot(req)
        for p in pages:
            self._pool.pin(p)
        lane.pages.extend(pages)
        table = numpy.full(self._max_pages, KVPagePool.SCRATCH,
                           numpy.int32)
        table[:len(pages)] = pages
        C = self.prefill_chunk
        n_full = (req.true_len - 1) // C
        for i in range(n_full):
            lane.pending.append((req.prompt[i * C:(i + 1) * C], i * C,
                                 False))
        tail = req.prompt[n_full * C:]
        if len(tail) < C:
            tail = numpy.pad(tail, (0, C - len(tail)))
        lane.pending.append((tail, n_full * C, True))
        self.metrics.record_queue_wait(time.monotonic() - req.t_enq)
        self._trace_admitted(req)
        entry = _Standby(lane, table)
        self._ring.append(entry)
        self._update_pool_gauges()
        self.metrics.set_gauge("standby_ring_occupancy",
                               len(self._ring))
        self.metrics.set_gauge_max("standby_ring_peak",
                                   len(self._ring))
        # the creation tick does its first chunk of prefill work too —
        # otherwise a C-chunk prompt takes C+1 boundaries to become
        # publishable and a one-boundary handoff window is always
        # missed by exactly the creation tick
        self._advance_standby_chunk(entry)

    def _advance_standby_chunk(self, entry):   # hot-path
        """One prompt chunk for a standby lane, into its own reserved
        pages; the tail chunk yields the entry's first token and marks
        it ready for publication."""
        lane = entry.lane
        req = lane.request
        tokens, start, is_tail = lane.pending.pop(0)
        last_idx = (req.true_len - 1 - start) if is_tail else 0
        t0 = time.monotonic()
        try:
            self._fault("engine.chunk")
            self._kv_pools, tok = self._chunk_jit(
                self.params, self._kv_pools,
                xfer.to_device(entry.table),
                xfer.to_device(tokens, numpy.int32),
                xfer.to_device(start, numpy.int32),
                xfer.to_device(last_idx, numpy.int32),
                *self._seed_args(req.seed))
            self._tfence(self._kv_pools, req.trace is not None)
        except Exception as e:   # noqa: BLE001 — fails THIS request
            self.metrics.record_error()
            self.warning("standby prefill failed: %s", e)
            if req.trace is not None:
                req.trace.tracer.add(
                    req.trace, "prefill.chunk", "prefill", t0,
                    time.monotonic(),
                    attrs={"start": start, "standby": True,
                           "error": str(e)})
            self._fail_standby(entry, e)
            return
        self.metrics.inc("prefill_dispatches")
        self._note_attn_dispatch()
        self.metrics.inc("prefill_tokens",
                         (req.true_len - start) if is_tail
                         else len(tokens))
        # lint: allow(host-sync): enqueue-time EWMA by design; device wall rides traced spans (_tfence)
        self.metrics.record_decode_step(time.monotonic() - t0)
        if req.trace is not None:
            req.trace.tracer.add(
                req.trace, "prefill.chunk", "prefill", t0,
                time.monotonic(),
                attrs={"start": start, "tail": is_tail,
                       "standby": True,
                       "bucket": self.prefill_chunk, "paged": True,
                       "backend": self._backend})
        if not is_tail:
            entry.pos = lane.pending[0][1]
            return
        tok = int(xfer.to_host(tok))
        lane.emitted.append(tok)
        lane.remaining -= 1
        self.metrics.inc("tokens_out")
        self.metrics.record_ttft(time.monotonic() - req.t_enq)
        entry.pos = req.true_len
        entry.last = tok
        if lane.remaining == 0 or req.cancelled:
            self._ring.remove(entry)
            self._resolve_standby(entry)
            self.metrics.set_gauge("standby_ring_occupancy",
                                   len(self._ring))
            return
        entry.ready = True

    def _resolve_standby(self, entry):
        """A standby lane that FINISHED while never holding a slot
        (n_new=1 at the prefill tail, or armed and drained between two
        boundaries): pages home, future resolved — the ring twin of
        :meth:`_finish`."""
        self._release_lane(entry.lane)
        fut = entry.lane.request.future
        if not fut.cancelled():
            fut.version = self.weights_version
            fut.set_result(numpy.asarray(entry.lane.emitted,
                                         numpy.int32))

    def _drop_standby(self, entry):
        """Withdrawn standby entry: pages home, future cancelled."""
        if entry in self._ring:
            self._ring.remove(entry)
        self._release_lane(entry.lane)
        entry.lane.request.future.cancel()
        self.metrics.set_gauge("standby_ring_occupancy",
                               len(self._ring))

    def _fail_standby(self, entry, exc):
        """Fail one standby entry to its client: pages back to the
        pool leak-free, future resolved — ring occupants participate
        in a faulted dispatch exactly like lanes (the chaos
        fault-isolation discipline)."""
        if entry in self._ring:
            self._ring.remove(entry)
        self._release_lane(entry.lane)
        fut = entry.lane.request.future
        if not fut.cancelled():
            fut.set_exception(exc)
        self.metrics.set_gauge("standby_ring_occupancy",
                               len(self._ring))

    def _requeue_ring(self):
        """Swap application: standby entries were prefilled on the OLD
        weights — their KV is stale the moment the new tree installs,
        so they go back to the queue head WHOLE (fresh deadline, like
        :meth:`_requeue_active`: the wait was spent on work the deploy
        threw away — a pre-prefilled request must never 503 for it)
        and re-prefill on the new weights."""
        reqs = []
        fresh_deadline = time.monotonic() + self.deadline_s
        for entry in self._ring:
            lane = entry.lane
            self._release_lane(lane)
            req = lane.request
            if req.cancelled:
                req.future.cancel()
                continue
            req.deadline = max(req.deadline, fresh_deadline)
            if req.trace is not None:
                req.trace.tracer.instant(
                    req.trace, "swap.requeue", cat="engine")
                req.tspan = req.trace.tracer.begin(
                    req.trace, "queue.wait", cat="queue",
                    attrs={"engine": self.name, "requeued": True})
            reqs.append(req)
        self._ring = []
        self.metrics.set_gauge("standby_ring_occupancy", 0)
        with self._cond:
            for req in reversed(reqs):
                self._queue.appendleft(req)
                self._queued_tokens += req.true_len
                self._queued_pages += req.pages
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self.metrics.set_gauge("queue_tokens", self._queued_tokens)
            self.metrics.set_gauge("queue_pages", self._queued_pages)
        self.metrics.inc("requests_requeued_for_swap", len(reqs))

    def _boundary_shed(self):
        """Deadline shedding at the MEGASTEP BOUNDARY (ISSUE 13
        satellite): one sweep of the whole queue per boundary, instead
        of the admission loop's per-pop head checks paying a lock round
        per tick.  A deadline expiring MID-megastep sheds at the NEXT
        boundary — the documented semantics: the fused program is never
        interrupted, a request already admitted keeps decoding (its
        deadline only ever governed queue wait), and a request whose
        tokens completed inside the megastep resolves its future before
        this sweep can ever see it.  Queue-token/page gauges re-read
        once per sweep, at the boundary, not per pop.

        ISSUE 19 window semantics: the worst-case shed LATENCY is one
        dispatch window, quoted from the megastep iteration CAP — the
        while mode realizes fewer iterations and exits early, so the
        cap bounds both modes (a fixed-K scan simply realizes the cap).
        The sweep also covers the standby ring: a pre-prefilled entry
        is ADMITTED work whose deadline only ever governed queue wait,
        so sitting in the ring past it must never 503 — its deadline is
        bumped forward (idempotent) so even a later swap requeue cannot
        shed work the engine already paid to prefill."""
        now = time.monotonic()
        for entry in self._ring:
            entry.lane.request.deadline = max(
                entry.lane.request.deadline, now + self.deadline_s)
        shed = []
        with self._cond:
            if not self._queue:
                return
            if all(now <= req.deadline or req.cancelled
                   for req in self._queue):
                return
            keep = collections.deque()
            for req in self._queue:
                if not req.cancelled and now > req.deadline:
                    shed.append(req)
                    self._queued_tokens -= req.true_len
                    self._queued_pages -= req.pages
                else:
                    keep.append(req)
            self._queue = keep
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self.metrics.set_gauge("queue_tokens", self._queued_tokens)
            if self._paged:
                self.metrics.set_gauge("queue_pages",
                                       self._queued_pages)
        window = self.megastep if self.megastep >= 2 else 1
        for req in shed:
            self.metrics.record_shed()
            self._trace_queue_end(req, "shed")
            req.future.set_exception(DeadlineExceeded(
                "prompt shed after %.3fs in queue (boundary sweep, "
                "window <= %d iterations)"
                % (time.monotonic() - req.t_enq, window)))

    def _worker(self):
        # the transfer-guard witness must be entered ON this thread
        # (JAX guard state is thread-local); a null context unarmed
        with xfer.guard():
            self._serve_loop()

    def _serve_loop(self):   # hot-path
        rr = 0
        while True:
            # per-tick fault site (latency spikes / replica freezes —
            # a freeze here wedges the worker exactly like a hung
            # device call, the shape the health prober must catch);
            # free when unarmed
            if self._faults is not None:
                try:
                    self._faults.fire("engine.tick")
                except Exception as e:   # noqa: BLE001 — injected
                    # a raised tick fault poisons the whole engine
                    # loop's turn: fail the in-flight lanes (the
                    # fault-isolation discipline) and keep ticking
                    self._fail_active(
                        [i for i, ln in enumerate(self._lanes)
                         if ln is not None], e)
            self._maybe_apply_swap()
            # the boundary sweep (one pass per loop turn = per
            # megastep when fused decode is on): sheds EVERY expired
            # queued request now, not just those the admission loop
            # happens to pop
            self._boundary_shed()
            self._admit_ring()
            self._admit()
            self._advance_ring()
            busy = [i for i, lane in enumerate(self._lanes)
                    if lane is not None]
            self.metrics.set_gauge("slots_busy", len(busy))
            self.metrics.set_gauge_max("slots_busy_peak", len(busy))
            if not busy:
                with self._cond:
                    if self._stop:
                        break
                    if self._ring:
                        # standby prefill still has host work — keep
                        # ticking so the ring drains/installs promptly
                        pass
                    elif not self._queue:
                        self._cond.wait(0.5)
                    elif self._pool_blocked:
                        # head request waiting on pages with no lane
                        # running to free any: only trie eviction or
                        # its deadline can resolve it — poll briefly so
                        # the shed fires on time without a hot spin
                        self._cond.wait(0.05)
                continue
            # chunked prefill interleaving: at most ONE prompt chunk per
            # tick (round-robin across prefilling lanes), then one
            # decode dispatch for the lanes that are past prefill — a
            # long prompt costs the decode lanes one chunk of latency
            # per token, never its whole prefill
            prefilling = [i for i in busy if self._lanes[i].pending]
            if prefilling:
                rr += 1
                self._advance_prefill(prefilling[rr % len(prefilling)])
            active = [i for i, lane in enumerate(self._lanes)
                      if lane is not None and not lane.pending]
            if not active:
                continue
            if self._whilestep_jit is not None:
                self._step_while(active)
            elif self._megastep_jit is not None:
                self._step_megastep(active)
            elif self._verify_jit is not None:
                self._step_speculative(active)
            else:
                self._step_plain(active)
        # drain: engine stopping fails whatever is still queued
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
            self._queued_tokens = 0
            self._queued_pages = 0
            swap = self._pending_swap
            self._pending_swap = None
        if swap is not None:
            # never strand a swap_weights caller on a stopping engine
            swap["exc"] = RuntimeError("LM engine stopped before the "
                                       "swap applied")
            swap["done"].set()
        for req in pending:
            self._trace_queue_end(req, "engine stopped")
            req.future.set_exception(RuntimeError("LM engine stopped"))
        for entry in list(self._ring):
            self._fail_standby(entry,
                               RuntimeError("LM engine stopped"))
        for slot, lane in enumerate(self._lanes):
            if lane is not None:
                lane.request.future.set_exception(
                    RuntimeError("LM engine stopped"))
                self._lanes[slot] = None
