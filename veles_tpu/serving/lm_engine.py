"""Continuous LM decode — slot-based batching over one shared KV cache.

The LM-traffic half of the serving subsystem (ISSUE 1).  ``serve_lm``'s
direct path decodes one prompt at a time: a second client waits for the
whole first decode even though the decode step is embarrassingly
batchable.  :class:`LMEngine` keeps a fixed pool of ``slots`` decode
lanes sharing one batched KV cache (per block: (slots, kv_heads,
max_len, head_dim)) and runs ONE vmapped decode step per token across
every active lane — vLLM-style continuous batching on a jit substrate:

- an arriving prompt is PREFILLED into any free slot mid-flight
  (``ops/transformer.py::prefill`` at a power-of-two prompt bucket,
  installed into the big cache at the slot index);
- every engine tick advances ALL active slots by one token via a single
  jitted vmap of ``ops/transformer.py::block_decode_step`` (per-slot
  positions — each lane is at its own depth in its own sequence);
- a finished sequence frees its slot immediately and the next queued
  prompt takes it, so decode throughput scales with slot count instead
  of serializing per prompt.

Decoding is GREEDY (temperature 0) — bit-identical to
``ops/transformer.py::generate`` for the same prompt, which is the
serving contract (sampled requests fall back to the direct path
upstream).  Compile count is bounded: one step program, one prefill
program per prompt bucket, one install program.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving.batcher import DeadlineExceeded, Overloaded
from veles_tpu.serving.metrics import ServingMetrics


class _Request:
    __slots__ = ("prompt", "true_len", "n_new", "future", "t_enq",
                 "deadline", "cancelled")

    def __init__(self, prompt, n_new, deadline_s):
        self.prompt = prompt          # (s,) int32, unpadded
        self.true_len = len(prompt)
        self.n_new = n_new
        self.future = Future()
        self.future.request = self    # cancellation handle
        self.t_enq = time.monotonic()
        self.deadline = self.t_enq + deadline_s
        self.cancelled = False


class _Slot:
    """Host-side lane state; device state lives in the shared caches."""

    __slots__ = ("request", "emitted", "remaining")

    def __init__(self, request):
        self.request = request
        self.emitted = []
        self.remaining = request.n_new


def prompt_bucket(true_len, max_len, floor=16):
    """Power-of-two prompt pad width (compile-count bound), capped at
    the cache length."""
    bucket = floor
    while bucket < true_len:
        bucket *= 2
    return min(bucket, max_len)


class LMEngine(Logger):
    """Slot-based continuous batching over ``params`` (a portable
    transformer param tree, see ``TransformerTrainer._to_portable``).

    One worker thread owns the device state; clients :meth:`submit`
    single prompts (or :meth:`generate` a batch) and block on futures.
    ``max_len`` pins the shared cache length: every request must satisfy
    ``len(prompt) + n_new <= max_len``.
    """

    def __init__(self, params, n_heads, max_len, slots=4, rope=False,
                 window=None, sinks=0, queue_depth=64, deadline_s=30.0,
                 metrics=None, name="lm"):
        import jax.numpy as jnp
        if slots < 1:
            raise ValueError("slots must be >= 1")
        self.name = name
        self.params = params
        self.n_heads = int(n_heads)
        self.max_len = int(max_len)
        self.slots = int(slots)
        self.rope = bool(rope)
        self.window = window
        self.sinks = int(sinks)
        self.queue_depth = int(queue_depth)
        self.deadline_s = float(deadline_s)
        self.metrics = metrics or ServingMetrics(name)
        self.metrics.set_gauge("slots_total", self.slots)
        self.metrics.set_gauge("slots_busy", 0)

        embed = params["embed"]
        d_model = embed.shape[1]
        head_dim = d_model // self.n_heads
        kv_heads = params["blocks"][0]["attn"]["wk"].shape[1] // head_dim
        cache_shape = (self.slots, kv_heads, self.max_len, head_dim)
        self._caches = [(jnp.zeros(cache_shape, embed.dtype),
                         jnp.zeros(cache_shape, embed.dtype))
                        for _ in params["blocks"]]
        #: per-slot device-facing scalars, host-owned between ticks
        self._pos = numpy.zeros(self.slots, numpy.int32)
        self._last = numpy.zeros(self.slots, numpy.int32)
        self._lanes = [None] * self.slots
        self._free = list(range(self.slots))

        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._thread = None
        self._stop = False
        self._build_jits()

    # ------------------------------------------------------------- jitted core
    def _build_jits(self):
        import jax
        import jax.numpy as jnp
        from veles_tpu.ops.transformer import (block_decode_step,
                                               head_logits, prefill)
        n_heads, max_len = self.n_heads, self.max_len
        rope, window, sinks = self.rope, self.window, self.sinks

        def prefill_one(params, prompt, true_len):
            # prompt (1, bucket) int32, true_len traced: positions
            # < true_len are exact under causal attention regardless of
            # pad content (see transformer._generate_impl), so one
            # compile serves every prompt length in the bucket
            h, caches = prefill(params, prompt, n_heads, max_len,
                                rope=rope, window=window, sinks=sinks)
            logits = head_logits(params, jax.lax.dynamic_slice_in_dim(
                h, true_len - 1, 1, axis=1))[:, 0, :]
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
            return tok, caches

        def install(caches, rows, slot):
            # scatter one prefilled lane (rows of (1,H,L,D)) into the
            # shared cache at a TRACED slot index — one compile total
            return [(k.at[slot].set(rk[0]), v.at[slot].set(rv[0]))
                    for (k, v), (rk, rv) in zip(caches, rows)]

        def step_one(params, cache_rows, tok, pos):
            # one lane, one token: feed ``tok`` at ``pos`` against this
            # lane's cache rows; vmapped below over the slot axis so
            # every lane advances in ONE dispatch at its own position
            x = jnp.take(params["embed"], tok[None], axis=0)[None]
            if "pos" in params:
                x = x + jax.lax.dynamic_slice_in_dim(
                    params["pos"], pos, 1, axis=0)[None]
            new_rows = []
            for blk, (kc, vc) in zip(params["blocks"], cache_rows):
                x, kc, vc = block_decode_step(
                    blk, x, kc[None], vc[None], pos, n_heads, rope=rope,
                    window=window, sinks=sinks)
                new_rows.append((kc[0], vc[0]))
            logits = head_logits(params, x)[0, 0, :]
            return new_rows, jnp.argmax(logits).astype(jnp.int32)

        self._prefill_jit = jax.jit(prefill_one)
        self._install_jit = jax.jit(install)
        self._step_jit = jax.jit(jax.vmap(step_one,
                                          in_axes=(None, 0, 0, 0)))

    # --------------------------------------------------------------- lifecycle
    def start(self):
        import jax.numpy as jnp
        # warm the step program (and the smallest prompt bucket) before
        # traffic: the discarded warmup writes land at pos 0 of free
        # slots, which the next prefill overwrites before they are ever
        # attended
        tok, rows = self._prefill_jit(
            self.params,
            jnp.zeros((1, prompt_bucket(1, self.max_len)), jnp.int32),
            jnp.asarray(1, jnp.int32))
        self._caches = self._install_jit(self._caches, rows,
                                         jnp.asarray(0, jnp.int32))
        self._caches, _ = self._step_jit(
            self.params, self._caches,
            jnp.zeros(self.slots, jnp.int32),
            jnp.ones(self.slots, jnp.int32))
        self._stop = False
        self._thread = threading.Thread(target=self._worker, daemon=True,
                                        name="lm-engine-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None

    # ------------------------------------------------------------------ client
    def submit(self, prompt, n_new):
        """Queue one prompt ((s,) ints) for ``n_new`` greedy tokens;
        returns a Future resolving to the (n_new,) continuation."""
        prompt = numpy.asarray(prompt, numpy.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if n_new < 1:
            raise ValueError("n_new must be >= 1")
        if len(prompt) + n_new > self.max_len:
            raise ValueError("prompt %d + n_new %d exceeds the engine "
                             "cache length %d"
                             % (len(prompt), n_new, self.max_len))
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("LM engine is not running")
            if len(self._queue) >= self.queue_depth:
                self.metrics.record_reject()
                raise Overloaded()
            req = _Request(prompt, int(n_new), self.deadline_s)
            self._queue.append(req)
            self.metrics.record_enqueue()
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self._cond.notify()
        return req.future

    def generate(self, prompts, n_new):
        """Decode a whole (b, s) prompt batch; returns (b, s + n_new)
        int32 — prompt plus greedy continuation per row (rows decode
        concurrently across slots).  All-or-nothing: if a later row is
        refused (Overloaded/...), the rows already queued are CANCELLED
        instead of decoding to discarded results — a rejected batch must
        not keep consuming slots exactly when the engine is overloaded."""
        prompts = numpy.asarray(prompts, numpy.int32)
        futures = []
        try:
            for row in prompts:
                futures.append(self.submit(row, n_new))
            news = numpy.stack([f.result() for f in futures])
        except Exception:
            # one row refused (Overloaded) or failed (shed, prefill
            # fault): withdraw ALL siblings — they must not keep
            # consuming slots for output nobody will read
            for f in futures:
                self._cancel(f.request)
            raise
        return numpy.concatenate([prompts, news], axis=1)

    def _cancel(self, req):
        """Withdraw a request: dequeue it if still queued; if already in
        a slot, flag it so the worker frees the slot at the next tick."""
        req.cancelled = True
        with self._cond:
            try:
                self._queue.remove(req)
            except ValueError:
                return           # admitted (or done) — worker handles it
        req.future.cancel()

    # ------------------------------------------------------------------ worker
    def _admit(self):
        """Move queued prompts into free slots (prefill + install)."""
        import jax.numpy as jnp
        while self._free:
            with self._cond:
                req = self._queue.popleft() if self._queue else None
                self.metrics.set_gauge("queue_depth", len(self._queue))
            if req is None:
                return
            if req.cancelled:            # raced _cancel's dequeue
                req.future.cancel()
                continue
            if time.monotonic() > req.deadline:
                self.metrics.record_shed()
                req.future.set_exception(DeadlineExceeded(
                    "prompt shed after %.3fs in queue" % (
                        time.monotonic() - req.t_enq)))
                continue
            slot = self._free.pop()
            bucket = prompt_bucket(req.true_len, self.max_len)
            prompt = req.prompt
            if bucket > req.true_len:
                prompt = numpy.pad(prompt,
                                   (0, bucket - req.true_len))
            try:
                tok, rows = self._prefill_jit(
                    self.params, jnp.asarray(prompt[None], jnp.int32),
                    jnp.asarray(req.true_len, jnp.int32))
                self._caches = self._install_jit(
                    self._caches, rows, jnp.asarray(slot, jnp.int32))
            except Exception as e:   # noqa: BLE001 — fails THIS request
                # a prefill fault (bad bucket compile, device error)
                # must fail its own request, not wedge the engine
                self.metrics.record_error()
                self.warning("prefill failed: %s", e)
                self._free.append(slot)
                if not req.future.cancelled():
                    req.future.set_exception(e)
                continue
            self.metrics.record_queue_wait(
                time.monotonic() - req.t_enq)
            lane = _Slot(req)
            lane.emitted.append(int(tok))
            lane.remaining -= 1
            self._pos[slot] = req.true_len
            self._last[slot] = int(tok)
            self._lanes[slot] = lane
            if lane.remaining == 0:
                self._finish(slot)

    def _finish(self, slot):
        lane = self._lanes[slot]
        self._lanes[slot] = None
        self._free.append(slot)
        fut = lane.request.future
        if not fut.cancelled():          # withdrawn mid-decode
            fut.set_result(numpy.asarray(lane.emitted, numpy.int32))

    def _worker(self):
        import jax.numpy as jnp
        while True:
            self._admit()
            active = [i for i, lane in enumerate(self._lanes)
                      if lane is not None]
            self.metrics.set_gauge("slots_busy", len(active))
            if not active:
                with self._cond:
                    if self._stop:
                        break
                    if not self._queue:
                        self._cond.wait(0.5)
                continue
            # ONE dispatch advances every active lane by one token;
            # inactive lanes step too (their writes land at a frozen
            # position that the next prefill/decode overwrites before
            # attending — see the module docstring), so the step program
            # never respecializes on the active set
            try:
                self._caches, toks = self._step_jit(
                    self.params, self._caches,
                    jnp.asarray(self._last), jnp.asarray(self._pos))
                toks = numpy.asarray(toks)
            except Exception as e:   # noqa: BLE001 — fails the lanes
                # a step fault poisons every in-flight lane; fail them
                # to their clients and keep serving — never wedge with
                # futures that no one will ever resolve
                self.metrics.record_error()
                self.warning("decode step failed: %s", e)
                for slot in active:
                    lane = self._lanes[slot]
                    self._lanes[slot] = None
                    self._free.append(slot)
                    if not lane.request.future.cancelled():
                        lane.request.future.set_exception(e)
                continue
            self.metrics.record_dispatch(len(active))
            for slot in active:
                lane = self._lanes[slot]
                lane.emitted.append(int(toks[slot]))
                lane.remaining -= 1
                self._pos[slot] += 1
                self._last[slot] = int(toks[slot])
                if lane.remaining == 0 or lane.request.cancelled:
                    self._finish(slot)
        # drain: engine stopping fails whatever is still queued
        with self._cond:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            req.future.set_exception(RuntimeError("LM engine stopped"))
        for slot, lane in enumerate(self._lanes):
            if lane is not None:
                lane.request.future.set_exception(
                    RuntimeError("LM engine stopped"))
                self._lanes[slot] = None
