"""Fleet-level model management (ISSUE 11) — the publisher loop that
closes the trainer→serving gap.

The training stack publishes checkpoints through ``snapshotter.py``
(atomic temp-file + fsync + rename writes, a stable ``*_current.*``
pointer); the serving stack can hot-swap a live fleet through
``Router.deploy`` / ``LMEngine.swap_weights``.  Until now a human
connected the two.  :class:`ModelManager` is that human, as a loop:

- WATCH a snapshot directory on a cadence
  (``snapshotter.find_current`` — the same resolver ``--snapshot
  auto`` uses, so the manager follows exactly what a resumed run
  would), keyed by (path, mtime) so each published file is acted on
  once;
- VALIDATE + LOAD the checkpoint OFF the hot path: the default
  :func:`load_lm_params` unpickles the payload (the snapshotter's
  loader already rejects truncated/corrupt files loudly — and its
  atomic writes mean a half-written file can never be seen at all),
  digs the portable LM param tree out of the trainer unit's state,
  and :func:`validate_lm_params` refuses non-finite weights before
  they get near a serving engine;
- DEPLOY through ``Router.deploy`` (canary-first, parity-probed,
  auto-rollback — see ``serving/router.py``) or, for a bare engine,
  ``LMEngine.swap_weights`` — either way the decode loop never sees
  the load/validate cost, and a bad checkpoint is a rejected record
  plus a warning, never an outage.

Wired as ``serve_lm(model_dir=, canary=, auto_rollback=)`` and the CLI
``--serve-model-dir`` / ``--serve-canary`` /
``--serve-publish-interval`` flags: a trainer writing snapshots into a
directory and a fleet pointed at it is the whole continuous
training→serving loop, end to end.
"""

from __future__ import annotations

import os
import threading
import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.serving.metrics import ServingMetrics


def validate_lm_params(params):
    """Refuse a checkpoint whose weights could poison a fleet: the
    tree must look like a portable LM param tree (``embed`` +
    ``blocks``) and every array leaf must be finite.  Raises
    ValueError naming the offense; returns the leaf count when
    sound.  Structural compatibility with the SERVING tree (shapes,
    dtypes) is the swap's own check — this one catches what a swap
    cannot: a numerically-exploded checkpoint that would swap cleanly
    and serve garbage."""
    if not isinstance(params, dict) or "embed" not in params \
            or "blocks" not in params:
        raise ValueError(
            "not an LM param tree (need 'embed' and 'blocks' keys, "
            "got %s)" % (sorted(params) if isinstance(params, dict)
                         else type(params).__name__))
    from jax.tree_util import keystr, tree_flatten_with_path
    leaves, _ = tree_flatten_with_path(params)
    for path, leaf in leaves:
        arr = numpy.asarray(leaf)
        if arr.dtype.kind == "f" and not numpy.isfinite(arr).all():
            raise ValueError("param %s holds non-finite values — "
                             "refusing to publish" % keystr(path))
    return len(leaves)


def load_lm_params(path):
    """Extract the portable LM param tree from a snapshotter payload:
    scan the workflow state's units for the transformer trainer's
    ``state_dict`` (``{"params": {"embed": ..., "blocks": [...]}}`` —
    the same portable form ``serve_lm`` marshals at startup).
    Returns ``(params, payload)``; raises ValueError when no LM
    trainer state is present (a non-LM workflow's snapshot directory
    is a configuration error, not something to retry)."""
    from veles_tpu import snapshotter
    payload = snapshotter.import_(path)
    units = payload.get("state", {}).get("units", {})
    for state in units.values():
        params = state.get("params") if isinstance(state, dict) else None
        if isinstance(params, dict) and "embed" in params \
                and "blocks" in params:
            return params, payload
    raise ValueError(
        "no LM trainer params found in snapshot %s (units: %s) — is "
        "this an LM workflow's snapshot directory?"
        % (path, sorted(units) or "none"))


class ModelManager(Logger):
    """Watch ``model_dir`` and drive ``target`` (a Router, or a bare
    LMEngine) to the newest published checkpoint; see the module
    docstring.  ``start()`` polls every ``interval_s`` on a background
    thread; :meth:`poll_once` is public and synchronous so tests and
    operators can drive one watch→validate→deploy pass
    deterministically.

    ``load(path) -> params | (params, payload)`` and
    ``validate(params)`` override the checkpoint reader and the
    pre-deploy validation; ``canary`` / ``canary_fraction`` /
    ``watch_s`` / ``auto_rollback`` / ``drain`` / ``probe_prompt`` /
    ``probe_n_new`` forward to ``Router.deploy``.  Versions count up
    from the fleet's current ``weights_version``; a rolled-back
    deploy burns its number (the gauge history stays monotone)."""

    #: ISSUE 15 annotation: the manager holds no lock by design — its
    #: mutable state (_seen, _version, last_record) is owned by the
    #: poller thread (or the test driving ``poll_once()`` with the
    #: thread stopped); the deploy/swap targets do their own locking.
    _synchronized_externally = \
        "publisher poller thread (single owner; poll_once() callers " \
        "must hold the thread stopped)"

    def __init__(self, target, model_dir, interval_s=5.0, canary=1,
                 canary_fraction=0.25, watch_s=0.0, auto_rollback=True,
                 drain=False, prefix=None, load=None, validate=None,
                 probe_prompt=(1, 2, 3), probe_n_new=4,
                 name="lm_publisher", metrics=None):
        self.name = name
        self.target = target
        self.model_dir = model_dir
        self.interval_s = float(interval_s)
        self.canary = int(canary)
        self.canary_fraction = float(canary_fraction)
        self.watch_s = float(watch_s)
        self.auto_rollback = bool(auto_rollback)
        self.drain = bool(drain)
        self.prefix = prefix
        self._load = load or load_lm_params
        self._validate = validate or validate_lm_params
        self.probe_prompt = tuple(probe_prompt)
        self.probe_n_new = int(probe_n_new)
        self.metrics = metrics or getattr(target, "metrics", None) \
            or ServingMetrics(name)
        replicas = getattr(target, "replicas", [target])
        self._version = max(
            int(getattr(e, "weights_version", 0) or 0)
            for e in replicas)
        self._seen = None          # (path, mtime) last acted on
        #: the last poll's outcome record (deploy result / rejection)
        self.last_record = None
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------ lifecycle
    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="publisher-%s" % self.name)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception as e:   # noqa: BLE001 — loop must survive
                self.warning("publisher pass failed: %s", e)

    # ------------------------------------------------------------- the pass
    def poll_once(self):
        """One watch→validate→deploy pass.  Returns None when nothing
        new was published, otherwise a record dict: the deploy's own
        record (plus ``path``/``epoch``/``load_s``), or ``{"rejected":
        reason}`` for a checkpoint that failed validation.  A bad file
        is remembered as seen — the manager never hot-loops on it —
        but a TRANSIENT deploy failure (no live replicas, a racing
        deploy) is forgotten so the same checkpoint retries at the
        next poll."""
        from veles_tpu import snapshotter
        path = snapshotter.find_current(self.model_dir, self.prefix)
        if path is None:
            return None
        try:
            st = os.stat(path)
        except OSError:
            return None              # pruned between listdir and stat
        # nanosecond mtime + size: the *_current.* path never changes,
        # and two publishes inside one coarse-mtime tick must still
        # read as distinct
        key = (path, st.st_mtime_ns, st.st_size)
        if key == self._seen:
            return None
        self._seen = key
        t0 = time.monotonic()
        epoch = None
        try:
            loaded = self._load(path)
            params, payload = loaded if isinstance(loaded, tuple) \
                else (loaded, None)
            if payload is not None:
                epoch = payload.get("epoch")
            self._validate(params)
        except OSError as e:
            # transient I/O (flaky mount, file replaced mid-read):
            # forget the key so the next poll retries — a BAD file is
            # a ValueError from the loader/validator, never OSError
            self._seen = None
            self.metrics.inc("publish_retries")
            self.warning("checkpoint %s unreadable (%s): retrying "
                         "next poll", path, e)
            return {"path": path, "deployed": False, "retry": str(e)}
        except Exception as e:   # noqa: BLE001 — reject, keep serving
            self.metrics.inc("publish_rejected")
            self.warning("checkpoint %s rejected: %s", path, e)
            self.last_record = {"path": path, "deployed": False,
                                "rejected": str(e)}
            return self.last_record
        self._version += 1
        version = self._version
        self.info("publishing %s as v%d (epoch %s)", path, version,
                  epoch)
        try:
            if hasattr(self.target, "deploy"):
                rec = self.target.deploy(
                    params, version=version, canary=self.canary,
                    canary_fraction=self.canary_fraction,
                    watch_s=self.watch_s,
                    auto_rollback=self.auto_rollback, drain=self.drain,
                    probe_prompt=self.probe_prompt,
                    probe_n_new=self.probe_n_new)
                rec = dict(rec, deployed=not rec.get("rolled_back"))
            else:
                self.target.swap_weights(params, version=version,
                                         drain=self.drain)
                rec = {"version": version, "deployed": True,
                       "rolled_back": False}
        except ValueError as e:
            # structurally impossible for THIS fleet — permanent for
            # this file, stays seen (no hot-loop)
            self.metrics.inc("publish_rejected")
            self.warning("swap of %s refused: %s", path, e)
            rec = {"version": version, "deployed": False,
                   "rejected": str(e)}
        except Exception as e:   # noqa: BLE001 — transient, retry
            # a TRANSIENT deploy failure (fleet momentarily all
            # quarantined, another deploy in flight) must not burn the
            # checkpoint: forget it so the next poll retries — else
            # the last checkpoint of a finished run could be lost
            self._seen = None
            self.metrics.inc("publish_retries")
            self.warning("deploy of %s failed (%s): retrying next "
                         "poll", path, e)
            self.last_record = {"path": path, "deployed": False,
                                "retry": str(e)}
            return self.last_record
        rec.update(path=path, epoch=epoch,
                   load_s=round(time.monotonic() - t0, 4))
        self.metrics.inc("publishes_total")
        self.last_record = rec
        return rec
