"""Serving metrics — lock-cheap counters/histograms with a snapshot API.

The observability half of the serving subsystem (ISSUE 1): every engine
(micro-batcher, LM slot engine) owns one :class:`ServingMetrics` and
records per-request and per-batch facts into it — queue wait, dispatch
batch size, end-to-end latency, 429/shed counts, slot occupancy.
Recording is a few integer adds under one short-lived lock (no
allocation on the hot path beyond the bounded latency ring), so the
serving threads never serialize on observability.

Consumers read via :meth:`ServingMetrics.snapshot` (a plain dict with
p50/p95/p99 computed over a bounded reservoir of recent latencies) or
the module-level :func:`render_prometheus`, which renders every
registered instance in Prometheus text format — ``web_status.py``
serves that at ``GET /metrics`` so the dashboard and scrapers share
one source.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time

from veles_tpu.serving import lockcheck

#: default histogram bucket bounds (seconds) for queue-wait / latency
TIME_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
               0.5, 1.0, 2.5, 5.0, 10.0)

#: ONE monotonic origin per process: every snapshot (and every
#: telemetry/SLO/ledger endpoint, ISSUE 14) stamps ``sampled_at`` as
#: seconds since this instant, so two scrapes of ANY endpoint share a
#: join key and rate math over them is arithmetic, not guesswork
_ORIGIN = time.monotonic()


def monotonic_offset():
    """Seconds since the process's metrics origin — the ``sampled_at``
    stamp every observability endpoint shares (monotonic: immune to
    wall-clock steps; comparable only within one process)."""
    return time.monotonic() - _ORIGIN
#: default bucket bounds for dispatch batch sizes (powers of two)
SIZE_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class Histogram:
    """Fixed-bound histogram (``le`` upper bounds, +Inf implicit).

    NOT thread-safe on its own — the owning ServingMetrics' lock guards
    every observe/read (one lock for the whole instance is cheaper than
    one per histogram at these rates)."""

    __slots__ = ("bounds", "counts", "total", "sum")

    def __init__(self, bounds):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)   # last = overflow
        self.total = 0
        self.sum = 0.0

    def observe(self, value):
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.total += 1
        self.sum += value

    def snapshot(self):
        return {"buckets": {str(b): c for b, c in
                            zip(self.bounds + ("+Inf",), self._cum())},
                "count": self.total,
                "sum": self.sum,
                "mean": self.sum / self.total if self.total else 0.0}

    def _cum(self):
        """Cumulative counts per bound (the Prometheus convention)."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def _escape_label(value):
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return str(value).replace("\\", r"\\").replace('"', r'\"') \
                     .replace("\n", r"\n")


def _label_key(labels):
    """Canonical (sorted tuple) form of a labels dict — the internal
    key for labeled gauge/counter samples."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class ServingMetrics:
    """One engine's counters; create via :func:`get` to auto-register.

    ``labels`` (ISSUE 8) attaches constant Prometheus labels to EVERY
    sample this instance renders — data-parallel engine replicas share
    one family name (``name="lm"``) and differ only by
    ``labels={"replica": "0"}``, so scrapers see one ``# TYPE`` per
    family with one row per replica.  Individual gauges/counters can
    additionally carry per-sample labels via ``set_gauge(...,
    labels=)`` / ``inc(..., labels=)`` — the router's per-replica
    placement counters ride that path."""

    #: lock-discipline map (ISSUE 15): every counter, histogram, gauge
    #: and the latency reservoir — recorded from serving threads, read
    #: by snapshots/renderers/the telemetry sampler — lives under the
    #: one instance lock.
    _guarded_by = {
        "requests": "_lock", "responses": "_lock",
        "rejected": "_lock", "shed": "_lock", "errors": "_lock",
        "dispatches": "_lock", "rows": "_lock",
        "queue_wait": "_lock", "batch_size": "_lock",
        "latency": "_lock", "ttft": "_lock", "decode_step": "_lock",
        "counters": "_lock", "gauges": "_lock", "ewmas": "_lock",
        "_recent": "_lock", "_labeled_gauges": "_lock",
        "_labeled_counters": "_lock",
    }

    def __init__(self, name="serving", latency_window=4096, labels=None):
        self.name = name
        #: constant instance-level labels rendered on every sample
        self.labels = {str(k): str(v)
                       for k, v in (labels or {}).items()}
        self._lock = lockcheck.make_lock("metrics._lock")
        #: counters
        self.requests = 0        # admitted into a queue
        self.responses = 0       # completed successfully
        self.rejected = 0        # refused at admission (HTTP 429)
        self.shed = 0            # dropped from the queue past deadline
        self.errors = 0          # failed dispatches / handler errors
        self.dispatches = 0      # device dispatches (batches / steps)
        self.rows = 0            # rows across all dispatches
        #: histograms
        self.queue_wait = Histogram(TIME_BOUNDS)
        self.batch_size = Histogram(SIZE_BOUNDS)
        self.latency = Histogram(TIME_BOUNDS)
        #: LM fast-path histograms (ISSUE 4): time-to-first-token per
        #: request and wall seconds per decode dispatch
        self.ttft = Histogram(TIME_BOUNDS)
        self.decode_step = Histogram(TIME_BOUNDS)
        #: named event counters (prefix-cache hits, draft acceptance,
        #: attn_kernel_dispatches/attn_kernel_fallbacks — the ISSUE 7
        #: which-attention-path-ran pair, ...) — engines add theirs via
        #: :meth:`inc`; rendered as ``veles_serving_<name>_total``
        #: counter families (one ``# TYPE`` line per family across
        #: every engine, the strict-parser rule render_instances keeps)
        self.counters = {}
        #: bounded reservoir of recent end-to-end latencies (percentiles)
        self._recent = collections.deque(maxlen=latency_window)
        #: point-in-time values (queue depth, slot occupancy, ...)
        self.gauges = {}
        #: labeled samples: {(name, label_key): value} — rendered into
        #: the SAME family as the unlabeled sample of that name
        self._labeled_gauges = {}
        self._labeled_counters = {}
        #: exponentially-weighted moving averages of the latency facts
        #: (TTFT, decode-step wall) — the router's freshness-weighted
        #: placement signal (a cumulative mean never forgets a cold
        #: start; an EWMA tracks the replica as it is NOW)
        self.ewmas = {}

    # ------------------------------------------------------------- recording
    def record_enqueue(self):
        with self._lock:
            self.requests += 1

    def record_reject(self):
        with self._lock:
            self.rejected += 1

    def record_shed(self):
        with self._lock:
            self.shed += 1

    def record_error(self):
        with self._lock:
            self.errors += 1

    def record_dispatch(self, batch_rows, queue_waits=()):
        """One device dispatch of ``batch_rows`` rows; ``queue_waits``
        are the seconds each member request spent queued."""
        with self._lock:
            self.dispatches += 1
            self.rows += batch_rows
            self.batch_size.observe(batch_rows)
            for w in queue_waits:
                self.queue_wait.observe(w)

    def record_queue_wait(self, wait_s):
        with self._lock:
            self.queue_wait.observe(wait_s)

    def record_ttft(self, seconds):
        """Time from enqueue to the request's FIRST generated token."""
        with self._lock:
            self.ttft.observe(seconds)
            self._ewma("ttft", seconds)

    def record_decode_step(self, seconds):
        """Wall seconds of one decode/verify dispatch."""
        with self._lock:
            self.decode_step.observe(seconds)
            self._ewma("decode_step", seconds)

    def record_megastep(self, k, lanes, tokens, wasted_iterations):
        """One fused K-iteration decode dispatch (ISSUE 13):
        ``lanes`` lanes entered active, ``tokens`` real tokens came
        out, ``wasted_iterations`` lane-iterations ran frozen past an
        early exit.  Feeds the ``megastep_*`` counter family —
        ``megastep_dispatches`` / ``megastep_tokens`` are what the
        bench's dispatches/token column reads on megastep legs, and
        ``megastep_wasted_iterations`` / ``megastep_lane_iterations``
        give the ``megastep_waste_frac`` the K tradeoff is measured
        by."""
        with self._lock:
            for name, n in (("megastep_dispatches", 1),
                            ("megastep_tokens", tokens),
                            ("megastep_lane_iterations", k * lanes),
                            ("megastep_wasted_iterations",
                             wasted_iterations)):
                self.counters[name] = self.counters.get(name, 0) + n

    def _ewma(self, name, value, alpha=0.2):
        # caller-holds: _lock
        prev = self.ewmas.get(name)
        self.ewmas[name] = value if prev is None \
            else (1.0 - alpha) * prev + alpha * value

    def inc(self, name, n=1, labels=None):
        """Bump the named counter by ``n`` (created at zero on first
        use) — the LM fast-path facts (prefix_hit_tokens,
        draft_accepted, ...) that are not worth a dedicated slot.
        ``labels`` keeps a separately-keyed sample in the same family
        (the router's ``routed_requests{replica="i"}``)."""
        with self._lock:
            if labels:
                key = (name, _label_key(labels))
                self._labeled_counters[key] = \
                    self._labeled_counters.get(key, 0) + n
            else:
                self.counters[name] = self.counters.get(name, 0) + n

    def counter(self, name, labels=None):
        with self._lock:
            if labels:
                return self._labeled_counters.get(
                    (name, _label_key(labels)), 0)
            return self.counters.get(name, 0)

    def record_response(self, latency_s):
        with self._lock:
            self.responses += 1
            self.latency.observe(latency_s)
            self._recent.append(latency_s)

    def set_gauge(self, name, value, labels=None):
        with self._lock:
            if labels:
                self._labeled_gauges[(name, _label_key(labels))] = value
            else:
                self.gauges[name] = value

    def gauge(self, name, default=0):
        """Cheap point read of one gauge — the router's placement loop
        polls these (queue_depth, slots_busy, kv_pages_free) without
        paying a full snapshot."""
        with self._lock:
            return self.gauges.get(name, default)

    def ewma(self, name, default=0.0):
        """Point read of one EWMA (ttft / decode_step)."""
        with self._lock:
            return self.ewmas.get(name, default)

    def latency_quantile(self, q, min_samples=8):
        """Point read of a recent-latency quantile (the hedging
        threshold's tail estimate, ISSUE 10) — None until
        ``min_samples`` responses exist, so an empty router never
        hedges against a guess."""
        with self._lock:
            if len(self._recent) < min_samples:
                return None
            vals = sorted(self._recent)
        return _percentile(vals, q)

    def set_gauge_max(self, name, value):
        """High-water-mark gauge: keeps the largest value ever set —
        peak concurrent slot occupancy is what the fixed-KV-memory
        bench compares across layouts, and a sampled gauge would
        under-read it between scrapes."""
        with self._lock:
            prev = self.gauges.get(name)
            self.gauges[name] = value if prev is None \
                else max(prev, value)

    # --------------------------------------------------------------- reading
    @staticmethod
    def _flat_key(name, label_key):
        """JSON-safe key for a labeled sample: ``name{k="v",...}``."""
        return "%s{%s}" % (name, ",".join(
            '%s="%s"' % kv for kv in label_key))

    def snapshot(self):
        """Plain-dict snapshot (JSON-safe) with latency percentiles.
        Labeled gauge/counter samples appear under their family dicts
        as ``name{label="v"}`` keys; instance labels ride under
        ``labels``."""
        with self._lock:
            recent = sorted(self._recent)
            counters = dict(self.counters)
            counters.update({self._flat_key(n, lk): v
                             for (n, lk), v in
                             self._labeled_counters.items()})
            gauges = dict(self.gauges)
            gauges.update({self._flat_key(n, lk): v
                           for (n, lk), v in
                           self._labeled_gauges.items()})
            return {
                "name": self.name,
                "sampled_at": round(monotonic_offset(), 6),
                "labels": dict(self.labels),
                "ewma": dict(self.ewmas),
                "requests": self.requests,
                "responses": self.responses,
                "rejected": self.rejected,
                "shed": self.shed,
                "errors": self.errors,
                "dispatches": self.dispatches,
                "rows": self.rows,
                "queue_wait": self.queue_wait.snapshot(),
                "batch_size": self.batch_size.snapshot(),
                "latency": dict(self.latency.snapshot(),
                                p50=_percentile(recent, 0.50),
                                p95=_percentile(recent, 0.95),
                                p99=_percentile(recent, 0.99)),
                "ttft": self.ttft.snapshot(),
                "decode_step": self.decode_step.snapshot(),
                "counters": counters,
                "gauges": gauges,
            }

    def _label_str(self, extra=()):
        """The full Prometheus label set for one sample line: the
        engine name, this instance's constant labels (replica id), and
        any per-sample ``extra`` pairs — escaped, deterministic
        order."""
        items = [("engine", self.name)] + sorted(self.labels.items()) \
            + list(extra)
        return "{%s}" % ",".join('%s="%s"' % (k, _escape_label(v))
                                 for k, v in items)

    def _families(self):
        """[(family, kind, [sample lines])] — merged per family across
        engines by the renderers, so the exposition carries exactly ONE
        ``# TYPE`` line per metric family (strict parsers reject
        duplicates).  Labeled samples join the family of their base
        name — replicas and per-replica router counters never fork a
        second ``# TYPE`` line."""
        label = self._label_str()
        fams = []
        with self._lock:
            for cname in ("requests", "responses", "rejected", "shed",
                          "errors", "dispatches", "rows"):
                metric = "veles_serving_%s_total" % cname
                fams.append((metric, "counter",
                             ["%s%s %d" % (metric, label,
                                           getattr(self, cname))]))
            for name, value in sorted(self.counters.items()):
                metric = "veles_serving_%s_total" % name
                fams.append((metric, "counter",
                             ["%s%s %d" % (metric, label, value)]))
            for (name, lkey), value in sorted(
                    self._labeled_counters.items()):
                metric = "veles_serving_%s_total" % name
                fams.append((metric, "counter",
                             ["%s%s %d" % (metric,
                                           self._label_str(lkey),
                                           value)]))
            for hname in ("queue_wait", "batch_size", "latency",
                          "ttft", "decode_step"):
                hist = getattr(self, hname)
                metric = "veles_serving_%s" % hname
                lines = ["%s_bucket%s %d"
                         % (metric,
                            self._label_str((("le", str(bound)),)), cum)
                         for bound, cum in zip(hist.bounds + ("+Inf",),
                                               hist._cum())]
                lines.append("%s_sum%s %g" % (metric, label, hist.sum))
                lines.append("%s_count%s %d" % (metric, label,
                                                hist.total))
                fams.append((metric, "histogram", lines))
            for gname, value in sorted(self.gauges.items()):
                metric = "veles_serving_%s" % gname
                fams.append((metric, "gauge",
                             ["%s%s %g" % (metric, label, value)]))
            for (name, lkey), value in sorted(
                    self._labeled_gauges.items()):
                metric = "veles_serving_%s" % name
                fams.append((metric, "gauge",
                             ["%s%s %g" % (metric,
                                           self._label_str(lkey),
                                           value)]))
        return fams

    def render_prometheus(self):
        """This instance's metrics in Prometheus text format."""
        return render_instances([self])


# ------------------------------------------------------------------ registry
_registry = {}   # guarded-by: _registry_lock
_registry_lock = threading.Lock()


def _registry_key(metrics):
    """Registry identity: name + instance labels — replica instances
    sharing a family name (``lm`` with ``replica="0"/"1"``) coexist;
    a restarted engine with the same name AND labels replaces its
    row."""
    if not metrics.labels:
        return metrics.name
    return "%s{%s}" % (metrics.name, ",".join(
        "%s=%s" % kv for kv in sorted(metrics.labels.items())))


def register(metrics):
    """Make ``metrics`` visible to the global /metrics renderer (latest
    instance wins per name+labels — restarted engines replace their
    row)."""
    with _registry_lock:
        _registry[_registry_key(metrics)] = metrics
    return metrics


def get(name="serving"):
    """The registered instance for ``name``, created on first use."""
    with _registry_lock:
        if name not in _registry:
            _registry[name] = ServingMetrics(name)
        return _registry[name]


def new(name, labels=None):
    """A FRESH registered instance for ``name`` (+ optional constant
    ``labels``) — engine starts use this so a restarted server begins
    at zero instead of accumulating into the previous run's counters
    (the old row is replaced)."""
    return register(ServingMetrics(name, labels=labels))


def registered():
    with _registry_lock:
        return list(_registry.values())


def render_instances(instances, extra_lines=()):
    """Prometheus text for ``instances``, one ``# TYPE`` line per
    family with every engine's samples under it."""
    fams = {}    # family -> (kind, [lines]); dict preserves order
    for m in instances:
        for family, kind, lines in m._families():
            fams.setdefault(family, (kind, []))[1].extend(lines)
    out = []
    for family, (kind, lines) in fams.items():
        out.append("# TYPE %s %s" % (family, kind))
        out.extend(lines)
    out.extend(line.rstrip("\n") for line in extra_lines)
    return "\n".join(out) + "\n" if out else ""


def render_prometheus(extra_lines=()):
    """All registered engines (plus caller-supplied lines — web_status
    appends its workflow gauges) in Prometheus text format."""
    return render_instances(registered(), extra_lines)
