"""StandardWorkflow — the config→graph compiler.

Ref: veles/znicz/standard_workflow.py::StandardWorkflow [H] (SURVEY §2.3):
builds the full training graph (loader → forwards → evaluator → decision →
gds → repeater cycle, snapshotter/plotters off decision) from a declarative
``layers`` list like::

    [{"type": "all2all_tanh", "->": {"output_sample_shape": 100},
                              "<-": {"learning_rate": 0.03}},
     {"type": "softmax",      "->": {"output_sample_shape": 10},
                              "<-": {"learning_rate": 0.03}}]

Flat keys are also accepted (merged into "->"/"<-" by ownership).

Execution: the classic unit graph runs under the host scheduler (unit mode).
When ``fused=True`` (default) the accelerated segment (forwards + evaluator +
gds) is additionally traced ONCE into jitted train/eval steps
(``veles_tpu.compiled``) and the per-minibatch cycle dispatches those instead
of the individual unit runs — same numerics (identical pure functions), one
XLA dispatch per minibatch (SURVEY §7 design stance).
"""

from __future__ import annotations

from veles_tpu.config import get
from veles_tpu.workflow import Repeater
from veles_tpu.ops.nn_units import NNWorkflow, LAYER_TYPES, gd_class_for
from veles_tpu.ops.evaluator import EvaluatorSoftmax, EvaluatorMSE
from veles_tpu.ops.decision import DecisionGD, DecisionMSE

import inspect

# keys that are never user-routable (wired by the builder itself)
_RESERVED = {"self", "workflow", "forward", "need_err_input", "name"}


def _accepted_keys(cls):
    """Config keys a unit class accepts, from its __init__ chain."""
    keys = set()
    for klass in cls.__mro__:
        if klass is object:
            break
        init = klass.__dict__.get("__init__")
        if init is None:
            continue
        for pname, param in inspect.signature(init).parameters.items():
            if pname in _RESERVED or param.kind in (
                    param.VAR_KEYWORD, param.VAR_POSITIONAL):
                continue
            keys.add(pname)
    return keys


def parse_layer(layer):
    """Split one layer config dict into (type, fwd_kwargs, gd_kwargs).

    Flat keys are routed by introspecting which unit class accepts them
    (forward wins ties); explicit "->"/"<-" sub-dicts bypass routing — the
    reference's layer config shape (ref: veles/znicz/standard_workflow.py
    [H]).
    """
    from veles_tpu.ops.nn_units import LAYER_TYPES, gd_class_for
    layer = dict(layer)
    kind = layer.pop("type")
    cls = LAYER_TYPES.get(kind)
    if cls is None:
        raise ValueError("unknown layer type %r (known: %s)" %
                         (kind, ", ".join(sorted(LAYER_TYPES))))
    fwd = dict(layer.pop("->", {}))
    gd = dict(layer.pop("<-", {}))
    fwd_keys = _accepted_keys(cls)
    gd_keys = _accepted_keys(gd_class_for(cls))
    for key, value in layer.items():
        if key in fwd_keys:
            fwd[key] = get(value, value)
        elif key in gd_keys:
            gd[key] = get(value, value)
        else:
            raise ValueError(
                "layer type %r does not accept config key %r "
                "(forward keys: %s; gd keys: %s)" %
                (kind, key, ", ".join(sorted(fwd_keys)),
                 ", ".join(sorted(gd_keys - fwd_keys))))
    return kind, cls, fwd, gd


class StandardWorkflowBase(NNWorkflow):
    """Builds the standard supervised-training graph from config."""

    def __init__(self, workflow=None, name=None, loader_factory=None,
                 loader_config=None, layers=(), decision_config=None,
                 snapshotter_config=None, loss_function="softmax", fused=True,
                 grad_accum=1, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.layers_config = list(layers)
        self.loss_function = loss_function
        self.fused = fused
        #: microbatches per optimizer step (fused mode; see FusedRunner)
        self.grad_accum = grad_accum
        if grad_accum != 1 and not fused:
            # never drop an explicit setting silently
            self.warning("grad_accum=%s is inert in unit (non-fused) "
                         "mode — the per-unit path dispatches whole "
                         "minibatches", grad_accum)
        skip_kinds = sorted({l.get("type") for l in self.layers_config
                             if l.get("type") in ("residual",
                                                  "residual_proj")})
        if not fused and skip_kinds:
            raise ValueError(
                "layer type(s) %s need the fused engine (a skip edge "
                "cannot ride the per-unit err chain) — build with "
                "fused=True" % ", ".join("'%s'" % k for k in skip_kinds))
        self.snapshotter = None
        self._build(loader_factory, dict(loader_config or {}),
                    dict(decision_config or {}), snapshotter_config)

    # ------------------------------------------------------------------ build
    def _build(self, loader_factory, loader_config, decision_config,
               snapshotter_config):
        if loader_factory is None:
            raise ValueError("loader_factory is required")
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = loader_factory(self, name="loader", **loader_config)
        self.loader.link_from(self.repeater)

        self.link_forwards()
        self.link_evaluator()
        self.link_decision(decision_config)
        self.link_gds()
        if snapshotter_config is not None:
            self.link_snapshotter(dict(snapshotter_config))
        self.link_end_point()

    def link_forwards(self):
        prev = None
        for layer in self.layers_config:
            kind, cls, fwd_kwargs, _ = parse_layer(layer)
            unit = cls(self, **fwd_kwargs)
            if prev is None:
                unit.link_from(self.loader)
                unit.link_attrs(self.loader, ("input", "minibatch_data"))
            else:
                unit.link_from(prev)
                unit.link_attrs(prev, ("input", "output"))
            if getattr(unit, "IS_RESIDUAL_PROJ", False):
                # the projection's weights shape infers from the SKIP
                # source, not the main path: wire its output (acts[src]
                # = input of layer src = output of layer src-1, or the
                # loader data for src 0) as skip_input
                src = len(self.forwards) - unit.skip
                if src < 0:
                    raise ValueError(
                        "residual_proj at layer %d skips %d back — "
                        "before the chain input"
                        % (len(self.forwards), unit.skip))
                if src == 0:
                    unit.link_attrs(self.loader,
                                    ("skip_input", "minibatch_data"))
                else:
                    unit.link_attrs(self.forwards[src - 1],
                                    ("skip_input", "output"))
            self.forwards.append(unit)
            prev = unit

    def link_evaluator(self):
        last = self.forwards[-1]
        if self.loss_function == "softmax":
            ev = EvaluatorSoftmax(self, name="evaluator")
            ev.link_attrs(self.loader, ("labels", "minibatch_labels"),
                          ("mask", "minibatch_mask"))
        elif self.loss_function == "mse":
            ev = EvaluatorMSE(self, name="evaluator")
            ev.link_attrs(self.loader, ("target", "minibatch_data"),
                          ("mask", "minibatch_mask"))
        else:
            raise ValueError("unknown loss_function %r" % self.loss_function)
        ev.link_from(last)
        ev.link_attrs(last, "output")
        self.evaluator = ev

    def link_decision(self, decision_config):
        cls = DecisionGD if self.loss_function == "softmax" else DecisionMSE
        dec = cls(self, name="decision", **decision_config)
        dec.link_from(self.evaluator)
        dec.link_attrs(self.loader, "minibatch_class", "minibatch_size",
                       "last_minibatch", "class_lengths", "epoch_number")
        dec.link_attrs(self.evaluator, "metrics")
        self.decision = dec

    def link_gds(self):
        """Backward chain in reverse layer order, closing the cycle."""
        prev_gd = None
        # err_input is only needed by gds BELOW; everything at or before the
        # first parameterized layer can skip that GEMM/conv (the reference's
        # need_err_input flag, extended past leading weightless layers like
        # augmentation/normalization)
        first_param = next(
            (i for i, f in enumerate(self.forwards) if f.has_params),
            len(self.forwards))
        for fwd in reversed(self.forwards):
            idx = self.forwards.index(fwd)
            _, _, _, gd_kwargs = parse_layer(self.layers_config[idx])
            gd_cls = gd_class_for(fwd)
            gd = gd_cls(self, forward=fwd,
                        need_err_input=idx > first_param,
                        **gd_kwargs)
            if prev_gd is None:
                gd.link_from(self.decision)
                gd.link_attrs(self.evaluator, "err_output")
            else:
                gd.link_from(prev_gd)
                gd.link_attrs(prev_gd, ("err_output", "err_input"))
            gd.link_attrs(self.loader, ("batch_size", "minibatch_size"))
            gd.gate_skip = self.decision.gd_skip | self.decision.complete
            self.gds.insert(0, gd)
            prev_gd = gd
        self.repeater.link_from(prev_gd if prev_gd is not None
                                else self.decision)

    def link_snapshotter(self, config):
        """Snapshotter at the tail of the backward chain (ref places it off
        decision — veles/znicz/standard_workflow.py [H] — but capturing the
        state AFTER the epoch's last weight commit is what makes resume
        bit-exact, so it hangs off the last gd; gate_skip propagation keeps
        it firing on valid/test minibatches too)."""
        from veles_tpu.snapshotter import Snapshotter
        config.setdefault("prefix", self.name)
        snap = Snapshotter(self, name="snapshotter", **config)
        snap.link_from(self.gds[0] if self.gds else self.decision)
        snap.link_attrs(self.decision, "improved", "complete")
        snap.link_attrs(self.loader, "epoch_number", "epoch_ended")
        self.snapshotter = snap
        return snap

    def link_plotters(self, output_dir="plots", weights_2d=True):
        """Attach the standard plotter set: metric curves, confusion matrix
        (softmax) and first-layer weight images, all redrawn at epoch ends.
        Ref: veles/znicz/standard_workflow.py's plotter wiring [H]; headless
        file output by default, ZMQ when a graphics_server is attached.
        """
        from veles_tpu.plotting_units import (AccumulatingPlotter,
                                              MatrixPlotter)
        from veles_tpu.nn_plotting_units import Weights2D
        plotters = []
        metric = "err_pct" if self.loss_function == "softmax" else "rmse"
        curve = AccumulatingPlotter(self, metric=metric,
                                    output_dir=output_dir, name="plot_curve")
        curve.input = self.decision
        plotters.append(curve)
        if self.loss_function == "softmax":
            confusion = MatrixPlotter(self, output_dir=output_dir,
                                      name="plot_confusion")
            confusion.input = self.decision
            plotters.append(confusion)
        if weights_2d:
            w2d = Weights2D(self, output_dir=output_dir, name="plot_weights")
            w2d.input = next((f for f in self.forwards if f.has_params),
                             self.forwards[0])
            plotters.append(w2d)
        for plotter in plotters:
            plotter.link_from(self.decision)
        self.plotters = plotters
        return plotters

    def link_end_point(self):
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete

    # ------------------------------------------------------------------ fused
    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        if self.fused:
            runner = getattr(self, "_fused_runner", None)
            if runner is None:
                from veles_tpu.compiled import FusedRunner
                self._fused_runner = FusedRunner(
                    self, grad_accum=self.grad_accum)
                self._fused_runner.install()
            else:
                # re-initialize (e.g. initialize() then Launcher.boot):
                # a second install() would add a DUPLICATE FusedStep
                # whose stale runner re-dispatches every minibatch with
                # frozen weights and clobbers the metrics — keep the
                # installed graph and just refresh the device state
                # from the (possibly re-initialized) unit Vectors
                runner.state = runner._pull_state()
        return self

    def snapshot_state(self):
        # during a fused run the unit Vectors lag the device state; sync
        # before collecting so snapshots always see the live weights
        # (SPMD: gather from the mesh first — sync_to_runner includes
        # the unit sync)
        trainer = getattr(self, "_sharded_trainer", None)
        runner = getattr(self, "_fused_runner", None)
        if trainer is not None:
            trainer.sync_to_runner()
        elif runner is not None:
            runner.sync_to_units()
        return super().snapshot_state()

    def load_snapshot_state(self, state):
        super().load_snapshot_state(state)
        # restored weights live in the unit Vectors; refresh the fused
        # runner's device state so the next step trains from them
        runner = getattr(self, "_fused_runner", None)
        if runner is not None:
            runner.state = runner._pull_state()
        # fine-tune semantics: a snapshot taken at completion restores
        # complete=True, but the CURRENT config may allow more epochs
        # (--snapshot with a raised max_epochs, ref resume ergonomics) —
        # re-evaluate the stopping condition against the current limits
        dec = self.decision
        if dec is not None and bool(dec.complete):
            if not dec.reevaluate_complete(int(self.loader.epoch_number)):
                dec.complete.set(False)


class StandardWorkflow(StandardWorkflowBase):
    """The user-facing standard workflow (reference class name parity)."""
