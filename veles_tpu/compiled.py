"""Fused execution of the accelerated segment of a StandardWorkflow.

SURVEY §7's central design move: the reference dispatched one OpenCL/CUDA
kernel per unit per minibatch; here the whole steady-state inner cycle
(forwards → evaluator → backwards → updates) is traced ONCE into a jitted
``train_step(state, batch) -> (state, metrics)`` (plus an ``eval_step``), so
XLA fuses across layer boundaries and the host does a single dispatch per
minibatch.  The unit graph is left intact — the accelerated units are
gate-skipped and a ``FusedStep`` node executes in their place — so Decision
gating, snapshotting and plotting keep working unchanged (they are host-side
outer-graph logic, exactly like the reference's event loop).

The pure functions composed here are the SAME ``forward_fn``/``backward_fn``/
``update_fn``/``loss_fn`` methods the units jit individually in unit mode, so
fused and unit mode are numerically identical by construction.
"""

from __future__ import annotations

from veles_tpu.mutable import Bool
from veles_tpu.units import Unit
from veles_tpu.loader.base import TRAIN


class FusedRunner:
    """Builds and owns the fused step functions + device parameter state."""

    def __init__(self, wf, grad_accum=1):
        import jax
        self.wf = wf
        self.forwards = list(wf.forwards)
        self.evaluator = wf.evaluator
        self.gds = list(wf.gds)
        #: microbatches per optimizer step (>1 = gradient accumulation:
        #: the minibatch is split, grads — batch SUMS by convention —
        #: add across microbatches, ONE update applies; peak activation
        #: memory shrinks by the factor, enabling effective batches that
        #: do not fit in HBM at once)
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError("grad_accum must be >= 1")
        self.state = self._pull_state()
        # loss routing: softmax-style evaluators consume labels, MSE-style
        # consume a target (linked on the evaluator; for autoencoders it
        # aliases the loader's minibatch_data)
        from veles_tpu.ops.evaluator import EvaluatorMSE
        self._is_mse = isinstance(self.evaluator, EvaluatorMSE)
        self._has_stochastic = any(getattr(f, "STOCHASTIC", False)
                                   for f in self.forwards)
        # No donation in per-minibatch graph mode: the update is only
        # COMMITTED after Decision gates it (see FusedStep/FusedCommit), so
        # the previous state must stay alive.  The epoch-scan path donates.
        #: the configured per-minibatch train step (monolithic or
        #: gradient-accumulating) — the per-step jit AND the epoch scan
        #: both route through it, so grad_accum is never silently dropped
        self._step_fn = (self._train_step if self.grad_accum == 1
                         else self._train_step_accum)
        self._train = jax.jit(self._step_fn)
        self._eval = jax.jit(self._eval_step)

    # ----------------------------------------------------------------- state
    def _pull_state(self):
        """Collect per-layer optimizer state from the unit Vectors
        (weightless layers contribute an empty entry).  The GD unit owns
        the entry layout — params + velocity, plus solver accumulators for
        adagrad/adadelta (see GradientDescentBase.state_entry)."""
        return [gd.state_entry() if fwd.has_params else {}
                for fwd, gd in zip(self.forwards, self.gds)]

    def sync_to_units(self):
        """Write fused state back into the unit Vectors (for snapshots)."""
        for entry, fwd, gd in zip(self.state, self.forwards, self.gds):
            if fwd.has_params:
                gd.absorb_entry(entry)

    # ----------------------------------------------------------------- steps
    def _layer_rng(self, rng, i):
        import jax
        return None if rng is None else jax.random.fold_in(rng, i)

    def _forward_chain(self, state, x, rng=None, train=False):
        acts = [x]
        h = x
        for i, (fwd, entry) in enumerate(zip(self.forwards, state)):
            if getattr(fwd, "HAS_SKIP_EDGE", False):
                # skip-edge layers (residual / residual_proj) see the
                # whole activation list — the unit owns the math
                # (ops/residual.py chain_forward), the chain owns acts
                h = fwd.chain_forward(i, acts, entry,
                                      self._layer_rng(rng, i), train)
            else:
                h = fwd.apply_fused(h, entry, self._layer_rng(rng, i),
                                    train)
            acts.append(h)
        return acts

    def _loss(self, y, y_ref, mask):
        """y_ref: labels (classification) or the regression/AE target."""
        if self._is_mse:
            return self.evaluator.loss_fn(y, y_ref.reshape(y.shape), mask)
        return self.evaluator.loss_fn(y, y_ref, mask)

    def _eval_step(self, state, x, y_ref, mask):
        acts = self._forward_chain(state, x, rng=None, train=False)
        _, metrics = self._loss(acts[-1], y_ref, mask)
        return metrics

    def _grads_and_metrics(self, state, x, y_ref, mask, rng=None):
        """Forward + loss + backward WITHOUT updates: per-layer grad sums
        (None for weightless layers) and the metric sums.  The per-layer
        update in _train_step and the accumulate-then-update in
        _train_step_accum both consume this."""
        acts = self._forward_chain(state, x, rng=rng, train=True)
        err, metrics = self._loss(acts[-1], y_ref, mask)
        all_grads = [None] * len(self.forwards)
        # residual fan-out: a skip edge makes acts[src] TWO consumers'
        # input, so its error has two contributions — the main chain's
        # and the stashed skip error, merged when the walk reaches src
        pending = {}
        for i in range(len(self.forwards) - 1, -1, -1):
            if err is not None and (i + 1) in pending:
                err = err + pending.pop(i + 1)
            if err is None:
                # the first parameterized gd skipped err_input; everything
                # below it is weightless (see link_gds) — nothing to do
                break
            fwd = self.forwards[i]
            if getattr(fwd, "HAS_SKIP_EDGE", False):
                # the unit returns its main-path error, where to stash
                # the skip error, and its own grads (None if weightless)
                err, src, d_src, grads = fwd.chain_backward(
                    i, acts, state[i], err, self._layer_rng(rng, i))
                pending[src] = (pending[src] + d_src if src in pending
                                else d_src)
                all_grads[i] = grads
                continue
            gd, entry = self.gds[i], state[i]
            err_in, grads = gd.backward_fused(
                acts[i], acts[i + 1], err, entry, self._layer_rng(rng, i))
            all_grads[i] = grads
            err = err_in
        return all_grads, metrics

    def _apply_updates(self, state, all_grads, batch_size, step):
        new_state = list(state)
        for i, grads in enumerate(all_grads):
            if grads is not None:
                new_state[i] = self.gds[i].update_fused(
                    state[i], grads, batch_size, step)
        return new_state

    def _train_step(self, state, x, y_ref, mask, batch_size, rng=None,
                    step=0):
        all_grads, metrics = self._grads_and_metrics(state, x, y_ref, mask,
                                                     rng)
        return self._apply_updates(state, all_grads, batch_size,
                                   step), metrics

    def _train_step_accum(self, state, x, y_ref, mask, batch_size,
                          rng=None, step=0):
        """Gradient-accumulation step: the minibatch splits into
        ``grad_accum`` microbatches scanned on device; grad sums add
        (they are batch SUMS by convention, so accumulation is exact up
        to fp summation order), ``*_max`` metrics combine with maximum,
        the rest add, and ONE update applies with the full live batch
        size.  Stochastic layers draw a distinct key per microbatch
        (documented semantics — dropout granularity follows the
        microbatch).  The microbatch graph is traced ONCE (zeros-init
        carry via eval_shape) so the accum path does not double compile
        time."""
        import jax
        import jax.numpy as jnp
        k = self.grad_accum
        if x.shape[0] % k:
            raise ValueError("minibatch %d not divisible by grad_accum %d"
                             % (x.shape[0], k))

        def split(a):
            return (None if a is None
                    else a.reshape((k, a.shape[0] // k) + a.shape[1:]))

        xs, ys, ms = split(x), split(y_ref), split(mask)

        def micro(i):
            r = None if rng is None else jax.random.fold_in(rng, i)
            y_i = None if ys is None else ys[i]
            return self._grads_and_metrics(state, xs[i], y_i, ms[i], r)

        g_shapes, m_shapes = jax.eval_shape(micro, 0)
        g0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), g_shapes)
        m0 = {key: (jnp.full(s.shape, -jnp.inf, s.dtype)
                    if key.endswith("_max")
                    else jnp.zeros(s.shape, s.dtype))
              for key, s in m_shapes.items()}

        def body(carry, i):
            g_acc, m_acc = carry
            g_i, m_i = micro(i)
            g_acc = jax.tree.map(jnp.add, g_acc, g_i)
            m_acc = {key: (jnp.maximum(m_acc[key], m_i[key])
                           if key.endswith("_max")
                           else m_acc[key] + m_i[key]) for key in m_acc}
            return (g_acc, m_acc), None

        (all_grads, metrics), _ = jax.lax.scan(body, (g0, m0),
                                               jnp.arange(k))
        return self._apply_updates(state, all_grads, batch_size,
                                   step), metrics

    def measure_device_step_time(self, iters=10):
        """Steady-state device time of one fused train step, by re-running
        the last dispatched batch ``iters`` times and ending the window in
        a value fetch (``block_until_ready`` does not block through the
        TPU tunnel).  None until a train step has run.  Feeds the
        ``print_stats`` device-time line (SURVEY §5.1 profiling rebuild).

        The timing dispatches REAL train steps but their updated state is
        DISCARDED (``self._train`` does not donate and the result is never
        assigned) — printing stats can never move the final weights;
        pinned by tests/test_launcher.py::
        test_stats_measurement_never_moves_weights."""
        import time
        import numpy
        import jax
        args = getattr(self, "_last_train_args", None)
        if args is None:
            return None

        def fetch(tree):
            return numpy.asarray(jax.tree.leaves(tree)[0]).ravel()[0]

        _, metrics = self._train(self.state, *args)
        fetch(metrics)  # warm (already compiled; syncs pending work)
        begin = time.perf_counter()
        for _ in range(iters):
            _, metrics = self._train(self.state, *args)
        fetch(metrics)
        return (time.perf_counter() - begin) / iters

    def eval_forward(self):
        """Jitted eval-mode forward ``(state, x) -> last activation``,
        compiled once and shared (REST serving, ensemble combination)."""
        import jax
        if not hasattr(self, "_eval_forward_jit"):
            self._eval_forward_jit = jax.jit(
                lambda state, x: self._forward_chain(
                    state, x, rng=None, train=False)[-1])
        return self._eval_forward_jit

    # ----------------------------------------------------- epoch-scan (fast)
    # One device dispatch per EPOCH: lax.scan over the minibatch index
    # matrix with the dataset resident in HBM.  This is the pure TPU-native
    # steady state — zero host work between minibatches (the reference did
    # host scheduling + H2D upload per minibatch, SURVEY §3.1).
    def _epoch_train(self, state, data, labels, idx, mask, rng=None,
                     step0=0):
        import jax
        import jax.numpy as jnp

        def body(carry, mb):
            step, mb_idx, mb_mask = mb
            x = jnp.take(data, mb_idx, axis=0)
            # labels doubles as the target array for MSE/AE workflows
            y = (jnp.take(labels, mb_idx, axis=0)
                 if labels is not None else x)
            bs = mb_mask.sum().astype(jnp.int32)
            step_rng = (jax.random.fold_in(rng, step)
                        if rng is not None else None)
            carry, metrics = self._step_fn(carry, x, y, mb_mask, bs,
                                           step_rng, step0 + step)
            return carry, metrics

        steps = jnp.arange(idx.shape[0])
        state, stacked = jax.lax.scan(body, state, (steps, idx, mask))
        totals = jax.tree.map(lambda m: m.sum(axis=0), stacked)
        return state, totals

    def _epoch_eval(self, state, data, labels, idx, mask):
        import jax
        import jax.numpy as jnp

        def body(carry, mb):
            mb_idx, mb_mask = mb
            x = jnp.take(data, mb_idx, axis=0)
            y = (jnp.take(labels, mb_idx, axis=0)
                 if labels is not None else x)
            metrics = self._eval_step(carry, x, y, mb_mask)
            return carry, metrics

        _, stacked = jax.lax.scan(body, state, (idx, mask))
        return jax.tree.map(lambda m: m.sum(axis=0), stacked)

    def _epoch_chunk(self, k, state, data, labels, idx, mask, rng=None,
                     step0=0):
        """``k`` epochs in ONE device program: lax.scan over the epoch
        axis around ``_epoch_train``.  Matches ``k`` sequential
        ``train_epoch`` calls exactly (same per-epoch key folding by
        global step, pinned by tests) while paying the host->device
        dispatch round-trip once per chunk instead of once per epoch —
        the knob that matters when the link to the device is a tunnel
        with ~0.1-1 s per-execute latency.

        ``idx``/``mask`` of shape (B, mb) reuse ONE minibatch plan for
        every epoch in the chunk; shape (k, B, mb) gives each epoch its
        own plan (true per-epoch reshuffling, precomputed on the host),
        so chunking does not have to trade away shuffle-per-epoch SGD
        semantics."""
        import jax
        import jax.numpy as jnp
        per_epoch_plan = idx.ndim == 3
        steps = idx.shape[-2]

        def body(carry, xs):
            if per_epoch_plan:
                e, eidx, emask = xs
            else:
                e, eidx, emask = xs, idx, mask
            off = step0 + e * steps
            erng = (jax.random.fold_in(rng, off)
                    if rng is not None else None)
            carry, totals = self._epoch_train(carry, data, labels, eidx,
                                              emask, erng, off)
            return carry, totals

        xs = ((jnp.arange(k), idx, mask) if per_epoch_plan
              else jnp.arange(k))
        state, stacked = jax.lax.scan(body, state, xs)
        return state, stacked

    def epoch_chunk_fn(self, k):
        """Jitted ``(state, data, labels, idx, mask[, rng, step0]) ->
        (state, per-epoch metric totals stacked over the k epochs)``;
        donates state.  Compiled once per distinct ``k``."""
        import functools
        import jax
        cache = getattr(self, "_epoch_chunk_jits", None)
        if cache is None:
            cache = self._epoch_chunk_jits = {}
        if k not in cache:
            inner = jax.jit(functools.partial(self._epoch_chunk, k),
                            donate_argnums=(0,))

            def chunk(state, data, labels, idx, mask, rng=None, step0=0):
                import jax.numpy as jnp
                self.require_epoch_rng(rng)
                if idx.ndim == 3 and idx.shape[0] != k:
                    raise ValueError(
                        "per-epoch plan has %d epochs, chunk is %d"
                        % (idx.shape[0], k))
                return inner(state, data, labels, idx, mask, rng,
                             jnp.asarray(step0, jnp.int32))

            cache[k] = chunk
        return cache[k]

    def _epoch_chunk_eval(self, k, state, data, labels, idx, mask,
                          vidx, vmask, rng=None, step0=0,
                          eval_first=False, tidx=None, tmask=None):
        """``k`` (train epoch + validation eval) rounds in ONE program:
        the convergence loop's body, chunked.  Returns the updated state
        plus per-epoch TRAIN and VALID metric totals (k rows each), so a
        host-side early-stopping loop sees exactly the per-epoch values
        it would have fetched individually — at one dispatch per k
        epochs instead of 2k (the regime that matters through a ~0.4 s
        per-execute tunnel).  idx/mask as in ``_epoch_chunk`` ((B, mb)
        shared or (k, B, mb) per-epoch plans); vidx/vmask are the fixed
        validation plan.  ``eval_first`` evaluates valid BEFORE the
        epoch's training — the unit-graph loop's set order (the loader
        plans test → validation → train), which the epoch-scan CLI
        driver mirrors; the convergence bench keeps eval-after.
        ``tidx``/``tmask`` add a per-epoch TEST-set eval (ordered before
        valid, like the loader plans it); its stacked totals come back
        as the fourth output (None when no test plan is given)."""
        import jax
        import jax.numpy as jnp
        per_epoch_plan = idx.ndim == 3
        steps = idx.shape[-2]
        has_test = tidx is not None

        def evals(carry):
            test_totals = (self._epoch_eval(carry, data, labels, tidx,
                                            tmask) if has_test else None)
            val_totals = self._epoch_eval(carry, data, labels, vidx,
                                          vmask)
            return test_totals, val_totals

        def body(carry, xs):
            if per_epoch_plan:
                e, eidx, emask = xs
            else:
                e, eidx, emask = xs, idx, mask
            off = step0 + e * steps
            erng = (jax.random.fold_in(rng, off)
                    if rng is not None else None)
            if eval_first:
                test_totals, val_totals = evals(carry)
            carry, train_totals = self._epoch_train(
                carry, data, labels, eidx, emask, erng, off)
            if not eval_first:
                test_totals, val_totals = evals(carry)
            return carry, (train_totals, val_totals, test_totals)

        xs = ((jnp.arange(k), idx, mask) if per_epoch_plan
              else jnp.arange(k))
        state, (train_stack, val_stack, test_stack) = jax.lax.scan(
            body, state, xs)
        return state, train_stack, val_stack, test_stack

    def epoch_chunk_eval_fn(self, k, eval_first=False, donate=True):
        """Jitted ``(state, data, labels, idx, mask, vidx, vmask[, rng,
        step0, tidx, tmask]) -> (state, train totals stacked, val totals
        stacked, test totals stacked or None)``.
        Donates state unless ``donate=False`` (the epoch-scan CLI driver
        keeps the chunk-input state alive so a completion inside the
        chunk can be replayed exactly — see epoch_driver.py — without
        paying per-leaf device copies).  Compiled once per distinct
        ``(k, eval_first, donate)`` (plus a retrace when a test plan
        appears)."""
        import functools
        import jax
        cache = getattr(self, "_epoch_chunk_eval_jits", None)
        if cache is None:
            cache = self._epoch_chunk_eval_jits = {}
        if (k, eval_first, donate) not in cache:
            inner = jax.jit(functools.partial(self._epoch_chunk_eval, k,
                                              eval_first=eval_first),
                            donate_argnums=(0,) if donate else ())

            def chunk(state, data, labels, idx, mask, vidx, vmask,
                      rng=None, step0=0, tidx=None, tmask=None):
                import jax.numpy as jnp
                self.require_epoch_rng(rng)
                if idx.ndim == 3 and idx.shape[0] != k:
                    raise ValueError(
                        "per-epoch plan has %d epochs, chunk is %d"
                        % (idx.shape[0], k))
                return inner(state, data, labels, idx, mask, vidx,
                             vmask, rng, jnp.asarray(step0, jnp.int32),
                             tidx=tidx, tmask=tmask)

            cache[(k, eval_first, donate)] = chunk
        return cache[(k, eval_first, donate)]

    def window_scan_fn(self):
        """Jitted ``(state, data, labels, idx, mask[, rng, step0]) ->
        (state, window metric totals)``: ALL of a WINDOW's minibatches as
        one ``lax.scan`` device program over window-resident data —
        ``_epoch_train`` (and therefore ``_step_fn``) reused verbatim
        with ``idx`` indexing INTO the window arrays, so fused/graph
        numerics parity is preserved by construction.  This is the
        streaming epoch-scan inner program (see epoch_driver.py): the
        dataset streams through HBM one window at a time while the host
        stages the next window concurrently.

        Non-donating: the streaming driver keeps the final window's
        input state alive so a Decision completion can be replayed with
        the last minibatch's update discarded (graph-loop parity, same
        artifact the chunk driver reproduces).  Compiled once per
        distinct window geometry — a uniform window size plus one tail
        window means at most two traces per run."""
        import jax
        if not hasattr(self, "_window_scan_jit"):
            inner = jax.jit(self._epoch_train)

            def window_scan(state, data, labels, idx, mask, rng=None,
                            step0=0):
                import jax.numpy as jnp
                self.require_epoch_rng(rng)
                return inner(state, data, labels, idx, mask, rng,
                             jnp.asarray(step0, jnp.int32))

            self._window_scan_jit = window_scan
        return self._window_scan_jit

    def require_epoch_rng(self, rng):
        """Stochastic layers (dropout) need an explicit epoch rng — shared
        guard for the single-chip and SPMD epoch-scan entry points."""
        if self._has_stochastic and rng is None:
            raise ValueError(
                "this network has stochastic layers (dropout): "
                "pass rng=jax.random.PRNGKey(...) to train_epoch")

    def epoch_fns(self):
        """Jitted (train_epoch, eval_epoch): args (state, data, labels,
        idx (B,mb) int32, mask (B,mb) f32[, rng]); train donates state.
        Networks with stochastic layers (dropout) MUST pass rng to
        train_epoch — enforced with a clear error at call time."""
        import jax
        if not hasattr(self, "_epoch_train_jit"):
            inner = jax.jit(self._epoch_train, donate_argnums=(0,))

            def train_epoch(state, data, labels, idx, mask, rng=None,
                            step0=0):
                import jax.numpy as jnp
                self.require_epoch_rng(rng)
                # int32 device scalar: a bare python int would retrace the
                # epoch program once per distinct value
                return inner(state, data, labels, idx, mask, rng,
                             jnp.asarray(step0, jnp.int32))

            self._epoch_train_jit = train_epoch
            self._epoch_eval_jit = jax.jit(self._epoch_eval)
        return self._epoch_train_jit, self._epoch_eval_jit

    # ------------------------------------------------------------ graph hook
    def install(self):
        """Rewire the graph: gate-skip the accelerated units; FusedStep runs
        the traced step right after the loader, FusedCommit adopts the
        pending update AFTER Decision has gated it — exactly the reference's
        ordering, where GD units fire after Decision and are skipped by
        gd_skip/complete (ref: veles/znicz/standard_workflow.py [H])."""
        wf = self.wf
        always = Bool(True)
        for unit in self.forwards + [self.evaluator] + self.gds:
            unit.gate_skip = always
        fused = FusedStep(wf, self, name="fused_step")
        first_fwd = self.forwards[0]
        first_fwd.unlink_from(wf.loader)
        fused.link_from(wf.loader)
        first_fwd.link_from(fused)
        commit = FusedCommit(wf, self, name="fused_commit")
        commit.link_from(wf.decision)
        commit.gate_skip = wf.decision.gd_skip | wf.decision.complete
        wf.fused_step = fused
        wf.fused_commit = commit
        return fused


class FusedStep(Unit):
    """Executes one fused train/eval step per minibatch.

    For train minibatches the updated state is held PENDING; FusedCommit
    adopts it only if Decision lets the backward pass run.  Note the unit
    Vectors (weights/bias) are only synced back at snapshot time and at run
    end — mid-run host reads must go through the runner's state.
    """

    snapshot_attrs = ("train_steps",)

    def __init__(self, workflow, runner, **kwargs):
        super().__init__(workflow, **kwargs)
        self.runner = runner
        self.pending_state = None
        #: global train-minibatch counter feeding the lr policies
        self.train_steps = 0
        self._initialized = True

    def initialize(self, **kwargs):
        super().initialize(**kwargs)

    def run(self):
        import jax.numpy as jnp
        runner = self.runner
        loader = runner.wf.loader
        #: attached by the launcher under --distributed: minibatches
        #: route through the mesh (local rows -> global batch, GSPMD
        #: all-reduce on the sharded batch axis), same pending/commit
        #: ordering (ref: SURVEY §5.8 — the reference's master-side
        #: averaging, collapsed into the compiled step)
        trainer = getattr(runner.wf, "_sharded_trainer", None)
        x = loader.minibatch_data.devmem
        labels = (loader.minibatch_labels.devmem
                  if not loader.minibatch_labels.is_empty else None)
        mask = loader.minibatch_mask.devmem
        if runner._is_mse:
            y_ref = runner.evaluator.target.devmem
        else:
            y_ref = labels
        if (loader.minibatch_class == TRAIN
                and not getattr(runner.wf, "eval_only", False)):
            if runner._has_stochastic:
                from veles_tpu import prng
                rng = prng.get("dropout").key()
            else:
                rng = None
            if trainer is not None:
                self.pending_state, metrics = trainer.train_step_pending(
                    x, y_ref, mask, loader.minibatch_size, rng,
                    self.train_steps)
            else:
                args = (x, y_ref, mask,
                        jnp.asarray(loader.minibatch_size, jnp.int32),
                        rng, jnp.asarray(self.train_steps, jnp.int32))
                self.pending_state, metrics = runner._train(runner.state,
                                                            *args)
                runner._last_train_args = args  # measure_device_step_time
            self.train_steps += 1
        else:
            self.pending_state = None
            if trainer is not None:
                metrics = trainer.eval_step(x, y_ref, mask)
            else:
                metrics = runner._eval(runner.state, x, y_ref, mask)
        # decision reads these through its link_attrs alias on the evaluator
        runner.evaluator.metrics = metrics

    def stop(self):
        trainer = getattr(self.runner.wf, "_sharded_trainer", None)
        if trainer is not None:
            trainer.sync_to_runner()
        else:
            self.runner.sync_to_units()


class FusedCommit(Unit):
    """Adopts the pending update; gated like the GD units."""

    def __init__(self, workflow, runner, **kwargs):
        super().__init__(workflow, **kwargs)
        self.runner = runner
        self._initialized = True

    def initialize(self, **kwargs):
        super().initialize(**kwargs)

    def run(self):
        fused = self.runner.wf.fused_step
        if fused.pending_state is not None:
            trainer = getattr(self.runner.wf, "_sharded_trainer", None)
            if trainer is not None:
                trainer.state = fused.pending_state
            else:
                self.runner.state = fused.pending_state
            fused.pending_state = None
