"""CLI entry point: ``python -m veles_tpu <workflow> [<config>] [flags]``.

Ref: veles/__main__.py::Main + scripts/velescli.py [H] (SURVEY §2.1, §3.1).
Reference ergonomics preserved:

- ``<workflow>`` is a Python file or a dotted module (e.g.
  ``veles_tpu.samples.mnist``) exposing ``run(load, main)``;
- ``<config>`` is a Python file executed against the global ``root`` tree;
- any argument of the form ``root.a.b=value`` overrides a config leaf;
- ``--random-seed`` seeds every named PRNG stream;
- ``--snapshot`` resumes from a snapshot file;
- ``-d/--device`` picks the backend (tpu/cpu) — the reference's
  OpenCL/CUDA/numpy selection collapsed onto JAX platforms.

The master/slave flags of the reference became ``--distributed`` (SPMD over
``jax.distributed``; see veles_tpu/launcher.py).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys


def build_argparser():
    parser = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native dataflow ML framework "
                    "(capability parity with VELES)")
    parser.add_argument("workflow",
                        help="workflow .py file or dotted module with "
                             "run(load, main)")
    parser.add_argument("config", nargs="?", default=None,
                        help="config .py file executed against `root`")
    parser.add_argument("overrides", nargs="*", metavar="root.a.b=value",
                        help="config leaf overrides")
    parser.add_argument("--random-seed", type=int, default=None,
                        help="seed every named PRNG stream")
    parser.add_argument("-s", "--snapshot", default=None,
                        help="resume from this snapshot file, or 'auto' to "
                             "resume from the latest snapshot in the "
                             "workflow's snapshot directory (fresh run if "
                             "none exists) — crash recovery")
    parser.add_argument("-d", "--device", default=None,
                        choices=("tpu", "cpu"),
                        help="JAX platform to run on (default: auto)")
    parser.add_argument("--epoch-scan", type=int, default=0, nargs="?",
                        const=1, metavar="CHUNK",
                        help="train via the epoch-scan driver: each "
                             "CHUNK epochs run as ONE device program "
                             "(default CHUNK=1 when the flag is bare); "
                             "identical decision/metrics semantics, "
                             "snapshot granularity = CHUNK epochs — the "
                             "fast path when dispatch latency is high")
    parser.add_argument("--stream-window", type=int, default=0,
                        metavar="MINIBATCHES",
                        help="stream the dataset through device memory "
                             "in windows of this many minibatches: each "
                             "window's minibatches run as ONE device "
                             "program while a host thread stages the "
                             "next window (out-of-core epoch-scan for "
                             "RecordsLoader/LMDB datasets; implies "
                             "--epoch-scan)")
    parser.add_argument("--stage-ahead", type=int, default=1,
                        metavar="N",
                        help="with --stream-window: windows staged "
                             "ahead of the device (default 1 = classic "
                             "double buffering; more overlaps deeper at "
                             "N+1 windows of HBM)")
    parser.add_argument("--no-fused", action="store_true",
                        help="run the unit graph without the fused "
                             "compiled step (debugging)")
    parser.add_argument("--precision", default=None,
                        choices=("float32", "default", "bfloat16"),
                        help="matmul/conv operand precision: float32 = "
                             "fp32-HIGHEST (bit-parity with the reference"
                             "'s fp32 GEMMs), bfloat16 = bf16 operand "
                             "casts with fp32 accumulation — the "
                             "TPU-idiomatic fast path, ~4x on conv nets "
                             "at measured convergence parity (see "
                             "docs/PERF.md)")
    parser.add_argument("--distributed", action="store_true",
                        help="join a multi-host SPMD run "
                             "(jax.distributed.initialize)")
    parser.add_argument("--coordinator-address", default=None)
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument("--snapshot-dir", default=None,
                        help="enable periodic snapshotting into this dir")
    parser.add_argument("--snapshot-interval", type=int, default=1)
    parser.add_argument("--snapshot-compression", default="gz",
                        choices=("", "gz", "bz2", "xz"))
    parser.add_argument("--snapshot-keep-last", type=int, default=0,
                        help="retain only the newest N epoch snapshots "
                             "(0 keeps all; the *_current resume pointer "
                             "always survives)")
    parser.add_argument("--result-file", default=None,
                        help="write a JSON run summary here")
    parser.add_argument("--dump-config", action="store_true",
                        help="print the effective config tree and exit")
    parser.add_argument("--graph", default=None, metavar="FILE.dot",
                        help="write the unit graph as graphviz dot")
    parser.add_argument("--no-stats", action="store_true",
                        help="skip the per-unit run-time table")
    parser.add_argument("--profile", default=None, metavar="DIR",
                        help="capture a jax.profiler trace of the run into "
                             "DIR (view with tensorboard/xprof)")
    parser.add_argument("--optimize", default=None, metavar="GENERATIONS",
                        help="genetic hyperparameter search over Tune() "
                             "leaves: '<generations>' or "
                             "'<generations>:<population>'")
    parser.add_argument("--list-units", action="store_true",
                        help="list registered unit classes and exit")
    class _Version(argparse.Action):
        """Lazy: importing veles_tpu pulls in jax, and the platform env
        handling in main() must run before the first jax import."""
        def __call__(self, parser, *unused_a, **unused_k):
            import veles_tpu
            print("veles_tpu %s" % veles_tpu.__version__)
            parser.exit()

    parser.add_argument("--version", action=_Version, nargs=0,
                        help="print the framework version and exit")
    parser.add_argument("--events-file", default=None, metavar="FILE",
                        help="append structured log events (JSON lines) to "
                             "FILE — the dependency-free form of the "
                             "reference's mongo event sink")
    parser.add_argument("--events-mongo", default=None, metavar="ADDR",
                        help="stream structured log events to MongoDB at "
                             "ADDR (mongodb://...; requires pymongo)")
    parser.add_argument("--evaluate", action="store_true",
                        help="evaluation-only: one pass over every "
                             "dataset split with weight updates gated "
                             "off (pair with --snapshot to score a "
                             "trained model)")
    parser.add_argument("--web-status", type=int, default=None,
                        metavar="PORT",
                        help="serve the live dashboard (0 = ephemeral "
                             "port; prints WEBSTATUS <url>): per-process "
                             "rows, per-epoch metrics, workflow graph "
                             "view at /graph/<row>.svg")
    parser.add_argument("--web-status-url", default=None, metavar="URL",
                        help="report this process's rows to ANOTHER "
                             "dashboard instead of serving one (worker "
                             "processes of a multi-host run)")
    parser.add_argument("--web-status-host", default="127.0.0.1",
                        metavar="HOST",
                        help="interface --web-status binds (use 0.0.0.0 "
                             "so other hosts' workers can POST /report)")
    parser.add_argument("--serve", type=int, default=None, metavar="PORT",
                        help="after the run completes, serve the trained "
                             "workflow over HTTP (REST /predict; 0 = "
                             "ephemeral port) until interrupted — the "
                             "reference's snapshot-to-serving flow in one "
                             "command (train or --snapshot restore, then "
                             "serve)")
    parser.add_argument("--serve-batch", type=int, default=0,
                        metavar="MAX_BATCH",
                        help="with --serve: coalesce concurrent /predict "
                             "requests through the dynamic micro-batcher "
                             "(veles_tpu.serving) into padded batches of "
                             "up to MAX_BATCH rows; 0 = direct "
                             "one-dispatch-per-request serving")
    parser.add_argument("--serve-slots", type=int, default=0,
                        metavar="SLOTS",
                        help="with --serve on an LM workflow: decode up "
                             "to SLOTS prompts concurrently over one "
                             "shared KV cache (continuous batching); "
                             "0 = one prompt batch at a time")
    parser.add_argument("--serve-prefix-cache", type=int, default=0,
                        metavar="CHUNKS",
                        help="with --serve-slots: radix prefix cache "
                             "over prompt KV, capacity CHUNKS cached "
                             "chunks (LRU) — requests sharing a system "
                             "prompt / few-shot header reuse its "
                             "prefill instead of recomputing it; "
                             "0 = off")
    parser.add_argument("--serve-prefill-chunk", type=int, default=0,
                        metavar="TOKENS",
                        help="with --serve-slots: run prompt prefill "
                             "as TOKENS-sized chunks interleaved with "
                             "decode steps (bounded compile buckets, "
                             "no head-of-line blocking behind long "
                             "prompts); 0 = whole-prompt prefill at "
                             "power-of-two buckets")
    parser.add_argument("--serve-spec-k", type=int, default=0,
                        metavar="K",
                        help="with --serve-slots: prompt-lookup "
                             "speculative decoding — draft K tokens "
                             "from the sequence's own n-grams and "
                             "verify them in one dispatch (multiple "
                             "tokens/dispatch on repetitive text, "
                             "output bit-identical to greedy); 0 = "
                             "one token per dispatch")
    parser.add_argument("--serve-paged-kv", type=int, default=0,
                        metavar="PAGES",
                        help="with --serve-slots: paged KV cache — "
                             "store decode KV in PAGES fixed-size "
                             "pages (page = the prefill chunk; "
                             "max_len must divide by it) shared by "
                             "every lane through per-lane page "
                             "tables; prefix-cache hits become "
                             "zero-copy page references and slot "
                             "count stops being bounded by "
                             "slots*max_len memory (output still "
                             "bit-identical to greedy); -1 = size "
                             "the pool to the contiguous footprint "
                             "(slots * max_len / chunk pages, + the "
                             "reserved scratch page); 0 = "
                             "contiguous KV")
    parser.add_argument("--serve-megastep", type=int, default=0,
                        metavar="K",
                        help="with --serve-slots: fused multi-step "
                             "decode — advance every live lane K "
                             "tokens per device dispatch via one "
                             "jitted lax.scan program (with "
                             "--serve-spec-k the draft proposal and "
                             "verification fold in-graph too), moving "
                             "admission/deadline/completion/swap "
                             "handling to megastep boundaries; output "
                             "stays bit-identical to greedy.  0/1 = "
                             "one dispatch per token (default)")
    parser.add_argument("--serve-attn-kernel", default="off",
                        choices=("off", "auto", "force"),
                        metavar="MODE",
                        help="with --serve-slots and --serve-paged-kv: "
                             "run the engine's attention through the "
                             "Pallas serving kernels (flash-decode "
                             "over the paged KV pool + fused chunked "
                             "prefill; ops/pallas_kernels.py). 'auto' "
                             "= kernels on real TPU hardware, XLA "
                             "fallback elsewhere (logged once, "
                             "metered as attn_kernel_fallbacks); "
                             "'force' = kernels even off-TPU via "
                             "interpret mode (tests only — orders of "
                             "magnitude slower than the fallback); "
                             "'off' = the XLA path (default)")
    parser.add_argument("--serve-tp", type=int, default=0,
                        metavar="N",
                        help="with --serve-slots: tensor-parallel "
                             "decode — run every engine program over "
                             "an N-device mesh (weights head-sharded, "
                             "KV cache/pool sharded head-wise; N must "
                             "divide the model's attention and KV "
                             "head counts; greedy output stays "
                             "bit-identical).  0 = single-device "
                             "(default)")
    parser.add_argument("--serve-replicas", type=int, default=1,
                        metavar="R",
                        help="with --serve-slots: R independent "
                             "data-parallel engine replicas (each on "
                             "its own device slice — R×max(tp,1) "
                             "devices when --serve-tp >= 2) behind a "
                             "metrics-driven router; /metrics gains "
                             "{replica=\"i\"} labels and responses a "
                             "per-row replica id")
    parser.add_argument("--serve-router", default="metrics",
                        choices=("metrics", "round_robin"),
                        help="with --serve-replicas: placement policy "
                             "— 'metrics' (default) weighs each "
                             "replica's live queue depth, resident KV "
                             "pages and TTFT/decode-step EWMAs; "
                             "'round_robin' ignores them (the skew "
                             "baseline)")
    parser.add_argument("--serve-health", action="store_true",
                        help="with --serve-slots: background health "
                             "prober per replica (staleness watch on "
                             "busy replicas, synthetic 1-token probe "
                             "on idle ones) that auto-quarantines a "
                             "failing replica via the router's drain "
                             "path and re-admits it after a cooldown "
                             "(half-open circuit breaker; "
                             "replica_health_state / "
                             "circuit_open_total on /metrics)")
    parser.add_argument("--serve-hedge", type=float, default=0.0,
                        metavar="SECONDS",
                        help="with --serve-slots: duplicate a request "
                             "still outstanding past SECONDS on a "
                             "second replica — first complete wins, "
                             "the loser is cancelled (greedy replicas "
                             "are bit-identical, so hedging moves "
                             "tail latency, never output); negative = "
                             "dynamic threshold (1.5x the live "
                             "latency p95); 0 = off (default)")
    parser.add_argument("--serve-retries", type=int, default=0,
                        metavar="N",
                        help="with --serve-slots: re-place a request "
                             "whose replica FAULTED (engine error — "
                             "not 429/503 sheds, not client errors) "
                             "on a different replica up to N times "
                             "with exponential jittered backoff; "
                             "0 = off (default, the fault fails to "
                             "the client)")
    parser.add_argument("--serve-model-dir", default=None,
                        metavar="DIR",
                        help="with --serve-slots: continuous "
                             "training→serving — watch DIR for the "
                             "snapshotter's *_current.* checkpoints "
                             "and hot-swap each new one across the "
                             "fleet with zero downtime (canary-first "
                             "deploy, parity probe, automatic "
                             "rollback; in-flight requests finish on "
                             "the weights they started on; replies "
                             "stamp the serving weights_version)")
    parser.add_argument("--serve-canary", type=int, default=1,
                        metavar="N",
                        help="with --serve-model-dir: swap N canary "
                             "replica(s) first and watch the live "
                             "health signals before ramping the rest "
                             "of the fleet (default 1)")
    parser.add_argument("--serve-publish-interval", type=float,
                        default=5.0, metavar="SECONDS",
                        help="with --serve-model-dir: how often the "
                             "publisher loop polls the snapshot "
                             "directory (default 5s)")
    parser.add_argument("--serve-canary-watch", type=float,
                        default=2.0, metavar="SECONDS",
                        help="with --serve-model-dir: how long the "
                             "deploy observes the canary's live "
                             "health signals (errors, decode-step/"
                             "TTFT EWMAs, the health circuit) with "
                             "traffic steered at it before ramping "
                             "the rest of the fleet; 0 = one "
                             "instantaneous signal check (default 2s)")
    parser.add_argument("--serve-trace", default="off",
                        metavar="MODE",
                        help="with --serve: end-to-end request "
                             "tracing (veles_tpu/serving/tracing.py) "
                             "— off|errors|all|sample:P.  Spans cover "
                             "the whole request path (HTTP root, "
                             "router attempts, queue wait, prefill "
                             "chunks, decode ticks, spec verify, COW "
                             "copies), the last N requests stay "
                             "reconstructable in a flight-recorder "
                             "ring (errors auto-dump a waterfall), "
                             "and GET /trace.json exports Chrome-"
                             "trace/Perfetto JSON "
                             "(tools/trace_report.py renders "
                             "waterfalls + the per-op cost ledger).  "
                             "'errors' retains only errored/deadline-"
                             "blown requests; 'sample:0.01' traces "
                             "1%% of traffic (default: off — zero "
                             "overhead)")
    parser.add_argument("--serve-trace-last", type=int, default=256,
                        metavar="N",
                        help="with --serve-trace: flight-recorder "
                             "ring size in requests (default 256)")
    parser.add_argument("--serve-telemetry", type=float, default=0.0,
                        nargs="?", const=1.0, metavar="SECONDS",
                        help="with --serve-slots: continuous "
                             "telemetry (veles_tpu/serving/"
                             "timeseries.py) — sample every serving "
                             "metrics family into bounded time-series "
                             "rings every SECONDS (bare flag = 1s): "
                             "counters as windowed rates, gauges, "
                             "histogram-delta p50/p95, plus runtime "
                             "gauges (live jit compile_programs, "
                             "process RSS, device memory, live MFU, "
                             "megastep waste fraction).  Served at "
                             "GET /timeseries.json?window=S; the "
                             "serving hot path has zero telemetry "
                             "sites (default: off)")
    parser.add_argument("--serve-slo", default=None, metavar="FILE",
                        help="with --serve-slots: declarative SLO "
                             "objectives (veles_tpu/serving/slo.py) "
                             "from a JSON file ('default' = the stock "
                             "availability/TTFT/decode-step/shed set) "
                             "— evaluated as multi-window error-"
                             "budget burn rates over the telemetry "
                             "store (implied on at 1s), ok/warn/page "
                             "state machine at GET /slo.json; with "
                             "--serve-health a page-level burn on one "
                             "replica feeds the health checker's "
                             "quarantine path")
    parser.add_argument("--serve-no-auto-rollback",
                        action="store_true",
                        help="with --serve-model-dir: do NOT roll a "
                             "failed canary back automatically — "
                             "leave the mixed fleet for the operator "
                             "(default: auto-rollback)")
    parser.add_argument("--fault-plan", default=None, metavar="FILE",
                        help="with --serve: arm the deterministic "
                             "fault-injection layer from a JSON plan "
                             "(veles_tpu/serving/faults.py — injected "
                             "dispatch errors, latency spikes, "
                             "freezes, admission storms, transient "
                             "HTTP errors at named sites).  Chaos/"
                             "test gear: every site is a no-op "
                             "without this flag")
    return parser


def load_workflow_module(spec):
    """Import the workflow module from a file path or dotted name."""
    if spec.endswith(".py") or os.path.sep in spec:
        name = os.path.splitext(os.path.basename(spec))[0]
        mod_spec = importlib.util.spec_from_file_location(name, spec)
        if mod_spec is None:
            raise ImportError("cannot load workflow file %r" % spec)
        module = importlib.util.module_from_spec(mod_spec)
        sys.modules[name] = module
        mod_spec.loader.exec_module(module)
        return module
    return importlib.import_module(spec)


def exec_config_file(path):
    """Execute a config file against the global root (reference semantics)."""
    from veles_tpu.config import root, Tune
    namespace = {"root": root, "Tune": Tune, "__file__": path}
    with open(path, "r", encoding="utf-8") as f:
        code = compile(f.read(), path, "exec")
    exec(code, namespace)


def main(argv=None):
    parser = build_argparser()
    # this image's argparse (3.10) cannot allocate positionals that
    # TRAIL optionals to the `overrides` nargs="*" slot ("prog wf
    # --flag x root.a.b=1" dies with "unrecognized arguments"):
    # collect override-shaped leftovers ourselves, reject the rest
    args, extra = parser.parse_known_args(argv)
    bad = [t for t in extra if t.startswith("-") or "=" not in t]
    if bad:
        parser.error("unrecognized arguments: %s" % " ".join(bad))
    args.overrides = list(args.overrides) + extra

    if args.device:
        # must win before the first jax import; a sitecustomize may force a
        # plugin platform, so also set the config knob once jax loads
        os.environ["JAX_PLATFORMS"] = args.device
        import jax
        jax.config.update("jax_platforms", args.device)

    if args.list_units:
        from veles_tpu.units import UnitRegistry
        import veles_tpu.ops  # noqa: F401 — populate the registry
        for name in sorted(UnitRegistry.units):
            print(name)
        return 0

    from veles_tpu import prng
    from veles_tpu.config import root, parse_override
    from veles_tpu.launcher import Launcher

    if args.events_file or args.events_mongo:
        from veles_tpu.logger import setup_logging
        try:
            setup_logging(events_file=args.events_file,
                          events_mongo=args.events_mongo)
        except (RuntimeError, OSError) as e:
            # missing pymongo / unreachable server / unwritable events file
            parser.error(str(e))

    if args.random_seed is not None:
        prng.seed_all(args.random_seed)

    if args.precision:
        from veles_tpu.ops import functional as F
        F.set_matmul_precision(args.precision)

    # tolerate overrides being swallowed into `config` when no config file
    overrides = list(args.overrides)
    if args.config and "=" in args.config and not os.path.exists(args.config):
        overrides.insert(0, args.config)
        args.config = None
    if args.config:
        exec_config_file(args.config)
    for token in overrides:
        parse_override(token)

    if args.dump_config:
        root.print_()
        return 0

    module = load_workflow_module(args.workflow)
    if not hasattr(module, "run"):
        raise SystemExit("workflow module %r has no run(load, main)"
                         % args.workflow)

    if args.optimize and (args.evaluate or args.serve is not None):
        parser.error("--optimize cannot be combined with --evaluate or "
                     "--serve (the GA drives its own training runs)")
    if args.optimize:
        try:
            from veles_tpu.genetics import optimize_cli
        except ImportError as e:
            raise SystemExit("--optimize requires veles_tpu.genetics: %s" % e)
        return optimize_cli(module, args)

    holder = {}

    def load(workflow_cls, **kwargs):
        if args.snapshot_dir:
            # CLI flags outrank any snapshotter section in the config file,
            # same precedence as root.a.b=value overrides
            # MERGE over any config-file snapshotter settings (e.g.
            # root.<name>.snapshotter.keep_last) instead of replacing —
            # flags win only for the keys they actually set
            cfg_snap = dict(kwargs.get("snapshotter_config") or {})
            cfg_snap.update({
                "directory": args.snapshot_dir,
                "interval": args.snapshot_interval,
                "compression": args.snapshot_compression,
            })
            if args.snapshot_keep_last:
                cfg_snap["keep_last"] = args.snapshot_keep_last
            kwargs["snapshotter_config"] = cfg_snap
        kwargs.setdefault("fused", not args.no_fused)
        wf = workflow_cls(None, **kwargs)
        holder["workflow"] = wf
        return wf

    def _servable(wf):
        """True when --serve will find a serving surface after training:
        an LM trainer (token continuation) or a forward chain."""
        if getattr(wf, "trainer", None) is not None and \
                hasattr(wf.trainer, "n_heads"):
            return True
        return bool(getattr(wf, "forwards", None))

    def main_():
        wf = holder["workflow"]
        if args.graph:
            wf.generate_graph(args.graph)
        if args.serve is not None and not _servable(wf):
            # fail BEFORE launcher.boot(): discovering an unservable
            # workflow only after the whole training run completes would
            # discard the session on a misconfiguration knowable up front
            parser.error("--serve: workflow %r has no forward chain or "
                         "LM trainer to serve" % wf.name)
        if args.web_status is not None or args.web_status_url:
            from veles_tpu.web_status import attach_web_status
            status = attach_web_status(
                wf, port=args.web_status or 0,
                report_url=args.web_status_url,
                host=args.web_status_host)
            if status is not None:
                print("WEBSTATUS http://%s:%d/"
                      % (args.web_status_host, status.port), flush=True)
        launcher = Launcher(
            wf, snapshot=args.snapshot, distributed=args.distributed,
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes, process_id=args.process_id,
            stats=not args.no_stats, profile=args.profile,
            evaluate=args.evaluate, epoch_scan=args.epoch_scan,
            stream_window=args.stream_window,
            stage_ahead=args.stage_ahead)
        holder["launcher"] = launcher
        launcher.boot()

    module.run(load, main_)

    launcher = holder.get("launcher")
    if launcher is not None and args.result_file:
        with open(args.result_file, "w", encoding="utf-8") as f:
            json.dump(launcher.result_summary(), f, indent=2, default=str)
    if launcher is not None and args.serve is not None:
        import threading
        import jax
        from veles_tpu.restful_api import RESTfulAPI
        if jax.process_index() != 0:
            # multi-host runs: exactly one serving endpoint (the same
            # single-writer rule the snapshotter follows)
            return 0
        wf = launcher.workflow
        if not _servable(wf):
            # unreachable for launcher-built workflows (checked before
            # boot); kept as the safety net for snapshot-restored ones
            parser.error("--serve: workflow %r has no forward chain or "
                         "LM trainer to serve" % wf.name)
        fault_plan = None
        if args.fault_plan:
            from veles_tpu.serving import FaultPlan
            fault_plan = FaultPlan.from_file(args.fault_plan)
        if getattr(wf, "trainer", None) is not None and \
                hasattr(wf.trainer, "n_heads"):
            # transformer-trainer workflows serve token continuation
            from veles_tpu.restful_api import serve_lm
            api = serve_lm(wf, port=args.serve, slots=args.serve_slots,
                           prefix_cache=args.serve_prefix_cache,
                           prefill_chunk=args.serve_prefill_chunk,
                           spec_k=args.serve_spec_k,
                           paged_kv=(True if args.serve_paged_kv < 0
                                     else args.serve_paged_kv),
                           attn_kernel=(0 if args.serve_attn_kernel
                                        == "off"
                                        else args.serve_attn_kernel),
                           megastep=args.serve_megastep,
                           tp=args.serve_tp,
                           replicas=args.serve_replicas,
                           router=args.serve_router,
                           health=args.serve_health,
                           hedge=args.serve_hedge,
                           retries=args.serve_retries,
                           fault_plan=fault_plan,
                           model_dir=args.serve_model_dir,
                           publish_interval_s=(
                               args.serve_publish_interval),
                           canary=args.serve_canary,
                           canary_watch_s=args.serve_canary_watch,
                           trace=args.serve_trace,
                           trace_last=args.serve_trace_last,
                           telemetry=args.serve_telemetry,
                           slo=(True if args.serve_slo == "default"
                                else args.serve_slo),
                           auto_rollback=(
                               not args.serve_no_auto_rollback))
        else:
            api = RESTfulAPI(
                wf, normalizer=getattr(wf.loader, "normalizer", None),
                faults=fault_plan)
            if args.serve_batch > 0:
                # enable_batching forwards api.faults, so the plan's
                # batcher.* sites arm alongside http.request
                api.enable_batching(max_batch=args.serve_batch)
            api.start(port=args.serve)
        # parseable by wrappers/tests; flushed before blocking
        print("SERVING http://127.0.0.1:%d/predict" % api.port, flush=True)
        try:
            threading.Event().wait()        # until SIGINT/SIGTERM
        except KeyboardInterrupt:
            pass
        finally:
            api.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
