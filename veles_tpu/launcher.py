"""Launcher — runs a workflow standalone or multi-host SPMD.

Ref: veles/launcher.py::Launcher [H] (SURVEY §2.1, §3.1): the reference's
launcher owned the Twisted reactor, created the device, ran the workflow in
standalone / ``--master`` / ``--slave`` modes and wired the auxiliary
services (graphics, web status).

TPU-native redesign (SURVEY §5.8): the master/slave control plane collapses
into SPMD — every host runs the SAME program under
``jax.distributed.initialize``; gradient averaging is the all-reduce XLA
inserts over ICI, and the loader shards its index space by
``process_index`` instead of receiving shards from a master.  Standalone is
the 1-process special case of the same code path.
"""

from __future__ import annotations

import time

from veles_tpu.logger import Logger


class Launcher(Logger):
    """Owns the workflow lifecycle: initialize → (restore) → run → report.

    Parameters
    ----------
    workflow: a built (not yet initialized) Workflow.
    snapshot: optional path — restore state after initialize (resume).
    distributed: join a multi-host run via ``jax.distributed`` and train
        lock-step SPMD over the global mesh — the loader yields each
        process's rows of the same global minibatch sequence
        (``shard_spmd``) and FusedStep routes through ShardedTrainer, so
        gradient averaging is the GSPMD all-reduce (the reference's
        ``--master``/``--slave`` pair, collapsed; the strided
        independent-shard mode stays available via ``Loader.shard`` for
        screening workloads).
    stats: print the per-unit run-time table at the end.
    """

    def __init__(self, workflow, snapshot=None, distributed=False,
                 coordinator_address=None, num_processes=None,
                 process_id=None, stats=True, profile=None,
                 evaluate=False, epoch_scan=0, stream_window=0,
                 stage_ahead=1):
        self.workflow = workflow
        self.snapshot = snapshot
        #: > 0: train via the epoch-scan driver (k-epoch chunks as one
        #: device program each) instead of the per-minibatch graph loop —
        #: see veles_tpu/epoch_driver.py for the exact semantics
        self.epoch_scan = int(epoch_scan or 0)
        #: > 0: stream the dataset through HBM in windows of this many
        #: minibatches (one scan dispatch per window, the next window
        #: staged concurrently) — the epoch-scan driver's out-of-core
        #: mode; implies epoch_scan when set alone
        self.stream_window = int(stream_window or 0)
        if self.stream_window and not self.epoch_scan:
            self.epoch_scan = 1
        #: windows staged ahead of the device (staging thread pool size)
        self.stage_ahead = int(stage_ahead or 1)
        #: evaluation-only run (SURVEY §3.3 "resume/EVALUATE from
        #: snapshot"): one pass over every dataset split with ALL weight
        #: updates gated off — metrics come out, parameters don't move
        self.evaluate = evaluate
        self.distributed = distributed
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        self.process_id = process_id
        self.stats = stats
        #: directory for a jax.profiler trace of the run (open with
        #: tensorboard / xprof, or tools/trace_step.py's parser)
        self.profile = profile
        self.restored_payload = None
        self.run_seconds = None

    def boot(self, **kwargs):
        """The reference's Launcher.boot(): bring everything up and run."""
        wf = self.workflow
        mesh = None
        if self.distributed:
            from veles_tpu.parallel import (initialize_multihost,
                                            make_mesh, spmd_loader_shard)
            index, count = initialize_multihost(
                self.coordinator_address, self.num_processes,
                self.process_id)
            # lock-step SPMD over ALL devices of the run: every process
            # plans the same global minibatch sequence and feeds its
            # local rows; gradient averaging is the all-reduce GSPMD
            # inserts over the sharded batch axis (the documented
            # --distributed semantics; the strided independent-shard
            # mode stays available programmatically via Loader.shard
            # for screening workloads)
            mesh = make_mesh()
            loader = getattr(wf, "loader", None)
            if loader is not None:
                loader.shard_spmd(*spmd_loader_shard(mesh))
            self.info("joined distributed run as process %d/%d "
                      "(%d-device mesh)", index, count,
                      mesh.devices.size)
        wf.initialize(**kwargs)
        if mesh is not None:
            runner = getattr(wf, "_fused_runner", None)
            if runner is None:
                raise ValueError("--distributed training needs a fused "
                                 "workflow (drop --no-fused)")
            from veles_tpu.parallel import ShardedTrainer
            wf._sharded_trainer = ShardedTrainer(runner, mesh)
        snapshot = self.snapshot
        if snapshot == "auto":
            # resume from the latest published snapshot of this workflow's
            # snapshotter directory, or start fresh if none exists yet —
            # the crash-recovery half of SURVEY §5.3 (drop_slave downgrade:
            # kill-and-resume instead of master-side job reissue)
            from veles_tpu import snapshotter
            snap_unit = getattr(wf, "snapshotter", None)
            if snap_unit is None:
                raise ValueError("--snapshot auto needs a workflow with a "
                                 "snapshotter (set --snapshot-dir)")
            snapshot = snapshotter.find_current(snap_unit.directory,
                                                snap_unit.prefix)
            if snapshot is None:
                self.info("no snapshot in %s — starting fresh",
                          snap_unit.directory)
        if snapshot:
            from veles_tpu import snapshotter
            self.restored_payload = snapshotter.restore(wf, snapshot)
            self.info("resumed from %s (epoch %s)", snapshot,
                      self.restored_payload.get("epoch"))
            trainer = getattr(wf, "_sharded_trainer", None)
            if trainer is not None:
                # restore rewrote the unit Vectors + runner state on the
                # host; push it back out over the mesh
                trainer.reload_from_runner()
        if self.evaluate:
            from veles_tpu.mutable import Bool
            always = Bool(True)
            #: units and the fused step consult this flag: every
            #: minibatch takes the EVAL path (no dropout, no backward,
            #: no PRNG draws) regardless of its dataset split
            wf.eval_only = True
            for gd in getattr(wf, "gds", []):
                gd.gate_skip = always
            commit = getattr(wf, "fused_commit", None)
            if commit is not None:
                commit.gate_skip = always       # belt-and-braces
            snap = getattr(wf, "snapshotter", None)
            if snap is not None:
                snap.skip.set(True)   # scoring must not touch lineage
            dec = getattr(wf, "decision", None)
            if dec is None:
                raise ValueError("--evaluate needs a Decision-driven "
                                 "workflow")
            # exactly one more pass over the epoch plan, however many
            # epochs the (restored) run already saw; best_* bookkeeping
            # stays whatever training left it at
            dec.max_epochs = int(wf.loader.epoch_number) + 1
            dec.fail_iterations = None
            dec.freeze_best = True
            dec.complete.set(False)
        if self.epoch_scan and self.evaluate:
            raise ValueError("--epoch-scan is a TRAINING driver; "
                             "--evaluate already runs one scoring pass")
        runner = None
        if self.epoch_scan:
            from veles_tpu.epoch_driver import EpochScanDriver
            driver = EpochScanDriver(wf, chunk=self.epoch_scan,
                                     stream_window=self.stream_window,
                                     stage_ahead=self.stage_ahead)
            runner = driver.run
        begin = time.perf_counter()
        if self.profile:
            import jax.profiler
            with jax.profiler.trace(self.profile):
                (runner or wf.run)()
            self.info("profiler trace written to %s", self.profile)
        else:
            (runner or wf.run)()
        self.run_seconds = time.perf_counter() - begin
        self.info("workflow %r finished in %.2fs", wf.name, self.run_seconds)
        if self.stats:
            wf.print_stats()
        return wf

    # ------------------------------------------------------------------ intro
    def result_summary(self):
        """JSON-friendly run summary (the reference wrote --result-file)."""
        wf = self.workflow
        decision = getattr(wf, "decision", None)
        out = {"workflow": wf.name, "run_seconds": self.run_seconds}
        if decision is not None:
            out["best_metric"] = decision.best_metric
            out["best_epoch"] = decision.best_epoch
            if decision.epoch_metrics:
                out["last_epoch_metrics"] = {
                    set_name: {k: v for k, v in metrics.items()
                               if isinstance(v, (int, float))}
                    for set_name, metrics in decision.epoch_metrics[-1].items()
                }
        snap = getattr(wf, "snapshotter", None)
        if snap is not None and snap.destination:
            out["snapshot"] = snap.destination
        return out
