"""Forge server — HTTP transport for the model store.

Ref: veles/forge_server.py + forge_client.py [M] (SURVEY §2.1): the
reference ran a web service the forge client uploaded packages to and
fetched them from.  This is the stdlib-only equivalent: a threading HTTP
server over a store directory, speaking the same package format as
``veles_tpu.forge`` (one ``.forge.tar.gz`` per version, manifest inside).

Endpoints:
- ``GET  /list``            → JSON [[package_file_name, manifest], ...]
- ``GET  /fetch/<name>``    → newest package tarball named <name>
- ``POST /upload``          → request body is a package tarball; stored
  versioned by (manifest name, packaged_at), like ``forge.publish``.

Client helpers (``upload``, ``list_remote``, ``fetch_remote``) use
urllib — no third-party dependencies, usable from training scripts.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu import forge


class _ForgeHandler(BaseHTTPRequestHandler):
    server_version = "VelesTPUForge/1"

    # -- helpers -------------------------------------------------------------
    def _json(self, payload, status=200):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, status, message):
        self._json({"error": message}, status=status)

    def log_message(self, fmt, *args):  # route through the server's logger
        self.server.log("%s %s", self.address_string(), fmt % args)

    # -- GET -----------------------------------------------------------------
    def do_GET(self):
        store = self.server.store_dir
        if self.path == "/list":
            listing = [(os.path.basename(path), manifest)
                       for path, manifest in forge.list_store(store)]
            return self._json(listing)
        if self.path.startswith("/fetch/"):
            name = urllib.parse.unquote(self.path[len("/fetch/"):])
            for path, manifest in forge.list_store(store):
                if manifest["name"] == name:
                    size = os.path.getsize(path)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/gzip")
                    self.send_header("Content-Length", str(size))
                    self.send_header(
                        "X-Forge-Package", os.path.basename(path))
                    self.end_headers()
                    with open(path, "rb") as f:
                        shutil.copyfileobj(f, self.wfile)
                    return
            return self._error(404, "no package named %r" % name)
        return self._error(404, "unknown path %r" % self.path)

    # -- POST ----------------------------------------------------------------
    def do_POST(self):
        if self.path != "/upload":
            return self._error(404, "unknown path %r" % self.path)
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0:
            return self._error(400, "empty upload")
        if length > self.server.max_package_bytes:
            return self._error(413, "package exceeds %d bytes"
                               % self.server.max_package_bytes)
        # stage to a temp file, validate it IS a forge package (readable
        # manifest with safe member names), then publish atomically.
        # The staging suffix must NOT look like a package, or a concurrent
        # /list would try to read the half-written file.
        fd, tmp = tempfile.mkstemp(suffix=".upload.tmp",
                                   dir=self.server.store_dir)
        try:
            with os.fdopen(fd, "wb") as f:
                remaining = length
                while remaining:
                    chunk = self.rfile.read(min(65536, remaining))
                    if not chunk:
                        return self._error(400, "truncated upload")
                    f.write(chunk)
                    remaining -= len(chunk)
            try:
                manifest = forge.read_manifest(tmp)
                forge._safe_member(manifest["snapshot"])
                if "artifact" in manifest:
                    forge._safe_member(manifest["artifact"])
            except Exception as e:
                return self._error(400, "not a valid forge package: %s" % e)
            dest = forge.publish(tmp, self.server.store_dir)
            self._json({"stored": os.path.basename(dest),
                        "name": manifest["name"]})
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)


class ForgeServer:
    """Owns the HTTP server thread over a store directory."""

    def __init__(self, store_dir, host="127.0.0.1", port=0,
                 max_package_bytes=1 << 31):
        os.makedirs(store_dir, exist_ok=True)
        self._httpd = ThreadingHTTPServer((host, port), _ForgeHandler)
        self._httpd.store_dir = store_dir
        self._httpd.max_package_bytes = max_package_bytes
        from veles_tpu.logger import Logger
        logger = Logger()
        self._httpd.log = lambda fmt, *a: logger.debug(fmt, *a)
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return "http://%s:%d" % self._httpd.server_address[:2]

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)


# ------------------------------------------------------------------ client
def upload(package_path, base_url, timeout=60):
    """Upload a package to a forge server; returns the server's record.

    The file object streams as the request body (packages can be GBs —
    never buffered whole in RAM)."""
    size = os.path.getsize(package_path)
    with open(package_path, "rb") as f:
        req = urllib.request.Request(
            base_url.rstrip("/") + "/upload", data=f,
            headers={"Content-Type": "application/gzip",
                     "Content-Length": str(size)}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())


def list_remote(base_url, timeout=60):
    """[(package_file_name, manifest)] from a forge server."""
    with urllib.request.urlopen(base_url.rstrip("/") + "/list",
                                timeout=timeout) as resp:
        return [tuple(item) for item in json.loads(resp.read().decode())]


def fetch_remote(base_url, name, out_dir, timeout=60):
    """Download + unpack the newest package named ``name``; returns
    (manifest, snapshot_path) like ``forge.fetch``."""
    if not name or os.path.basename(name) != name:
        raise ValueError("unsafe package name %r" % (name,))
    os.makedirs(out_dir, exist_ok=True)
    url = base_url.rstrip("/") + "/fetch/" + urllib.parse.quote(name)
    package_path = os.path.join(out_dir, name + ".forge.tar.gz")
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        with open(package_path, "wb") as f:
            shutil.copyfileobj(resp, f)
    return forge.unpack(package_path, out_dir)
