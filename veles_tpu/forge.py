"""Forge — the model zoo: package, store, fetch trained workflows.

Ref: veles/forge_client.py / forge_server [M] (SURVEY §2.1): the reference
packaged a workflow (manifest + snapshot + sources) and uploaded it to a
forge server.  Redesign: a package is one tar.gz holding ``manifest.json``
+ the snapshot file; the "server" is a store directory (local path — or a
network mount; the reference's HTTP upload becomes a file copy, which is
what zero-egress TPU pods can actually use).

API: ``pack`` → package file; ``publish`` → store; ``list_store`` /
``fetch`` → retrieve; ``restore_package`` → live workflow.
"""

from __future__ import annotations

import json
import os
import shutil
import tarfile
import tempfile
import time

MANIFEST = "manifest.json"


def _safe_member(name):
    """Reject manifest-controlled member names that could escape the
    extraction directory (path traversal via '../' or absolute paths): a
    member must be a bare file name.  Packages are UNTRUSTED once fetched
    from a shared store."""
    if (not name or os.path.basename(name) != name
            or name in (os.curdir, os.pardir)):
        raise ValueError("unsafe member name in forge manifest: %r" % (name,))
    return name


def pack(snapshot_path, out_path, name=None, author=None, description="",
         metrics=None, extra_files=(), artifact_path=None):
    """Create a forge package from a snapshot file.

    ``artifact_path`` optionally bundles a StableHLO export artifact
    (veles_tpu.export) so the package can be SERVED framework-free as well
    as restored for resume/fine-tune (the reference's snapshot played both
    roles — SURVEY §3.3/§3.4)."""
    if not os.path.exists(snapshot_path):
        raise FileNotFoundError(snapshot_path)
    manifest = {
        "name": name or os.path.basename(snapshot_path).split("_")[0],
        "author": author or os.environ.get("USER", "unknown"),
        "description": description,
        "metrics": metrics or {},
        "snapshot": os.path.basename(snapshot_path),
        "packaged_at": time.time(),
        "format": 1,
    }
    if artifact_path is not None:
        if not os.path.exists(artifact_path):
            raise FileNotFoundError(artifact_path)
        manifest["artifact"] = os.path.basename(artifact_path)
    with tarfile.open(out_path, "w:gz") as tar:
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(manifest, f, indent=2)
            tmp = f.name
        tar.add(tmp, arcname=MANIFEST)
        os.unlink(tmp)
        tar.add(snapshot_path, arcname=manifest["snapshot"])
        if artifact_path is not None:
            tar.add(artifact_path, arcname=manifest["artifact"])
        for path in extra_files:
            tar.add(path, arcname=os.path.basename(path))
    return out_path


def read_manifest(package_path):
    with tarfile.open(package_path, "r:gz") as tar:
        member = tar.extractfile(MANIFEST)
        if member is None:
            raise ValueError("%s has no %s" % (package_path, MANIFEST))
        return json.load(member)


def unpack(package_path, out_dir):
    """Extract a package; returns (manifest, snapshot_path)."""
    os.makedirs(out_dir, exist_ok=True)
    with tarfile.open(package_path, "r:gz") as tar:
        tar.extractall(out_dir, filter="data")
    with open(os.path.join(out_dir, MANIFEST), encoding="utf-8") as f:
        manifest = json.load(f)
    return manifest, os.path.join(out_dir, _safe_member(manifest["snapshot"]))


def publish(package_path, store_dir):
    """Upload to the store (versioned by name + timestamp).

    Atomic: staged under a non-package suffix, then renamed — concurrent
    ``list_store`` readers (e.g. forge_server /list) never see a
    half-copied package."""
    manifest = read_manifest(package_path)
    os.makedirs(store_dir, exist_ok=True)
    dest = os.path.join(store_dir, "%s_%d.forge.tar.gz"
                        % (manifest["name"], int(manifest["packaged_at"])))
    staging = dest + ".publish.tmp"
    try:
        shutil.copyfile(package_path, staging)
        os.replace(staging, dest)
    except BaseException:
        if os.path.exists(staging):
            os.unlink(staging)
        raise
    return dest


def list_store(store_dir):
    """[(package_path, manifest)] sorted newest-first."""
    out = []
    if not os.path.isdir(store_dir):
        return out
    for fname in sorted(os.listdir(store_dir), reverse=True):
        if fname.endswith(".forge.tar.gz"):
            path = os.path.join(store_dir, fname)
            out.append((path, read_manifest(path)))
    return out


def fetch(store_dir, name, out_dir):
    """Fetch the newest package named ``name``; returns (manifest,
    snapshot_path)."""
    for path, manifest in list_store(store_dir):
        if manifest["name"] == name:
            return unpack(path, out_dir)
    raise KeyError("no package %r in %s" % (name, store_dir))


def load_artifact(package_path, out_dir=None):
    """Load the bundled export artifact of a package as an ExportedModel
    (framework-free serving); raises KeyError if the package has none.

    Only the artifact member is extracted — the (possibly multi-GB)
    training snapshot never touches disk on the serving path."""
    from veles_tpu.export import load_model
    manifest = read_manifest(package_path)
    if "artifact" not in manifest:
        raise KeyError("package %s carries no export artifact"
                       % package_path)
    artifact_name = _safe_member(manifest["artifact"])  # before mkdtemp
    cleanup = out_dir is None
    out_dir = out_dir or tempfile.mkdtemp(prefix="forge_")
    artifact_path = os.path.join(out_dir, artifact_name)
    try:
        with tarfile.open(package_path, "r:gz") as tar:
            member = tar.extractfile(manifest["artifact"])
            if member is None:
                raise ValueError("%s: manifest names artifact %r but the "
                                 "member is missing"
                                 % (package_path, manifest["artifact"]))
            os.makedirs(out_dir, exist_ok=True)
            with open(artifact_path, "wb") as f:
                shutil.copyfileobj(member, f)
        return load_model(artifact_path)
    finally:
        if cleanup:
            shutil.rmtree(out_dir, ignore_errors=True)


def restore_package(package_path, build, out_dir=None):
    """Unpack + restore into a live workflow: ``build()`` must return the
    initialized workflow (SURVEY §3.3: the snapshot is the artifact)."""
    from veles_tpu import snapshotter
    out_dir = out_dir or tempfile.mkdtemp(prefix="forge_")
    manifest, snapshot_path = unpack(package_path, out_dir)
    wf = build()
    snapshotter.restore(wf, snapshot_path)
    return wf, manifest
