"""VideoAE sample — autoencoder over synthetic video frames.

Ref: veles/znicz/samples VideoAE demo (SURVEY §2.3 samples row [H]): the
reference's zoo trained the deconv autoencoder stack on frames extracted
from video.  Videos are not shippable in a hermetic container, so the
TPU rebuild generates its "footage" — sequences of frames with a bright
blob moving along a per-sequence linear trajectory over a textured
background — which preserves what the demo exercises: the AE learns the
low-dimensional structure (blob position) shared by temporally adjacent
frames.  Real frames can be fed instead through ``loader/image.py``
(directory datasets) or ``loader/records.py`` without touching the model.

Frame synthesis is vectorized over (sequence, frame, pixel) — one numpy
broadcast, no python-per-frame loops — and the whole set lives in HBM
via FullBatchLoader, so the fused MSE step runs entirely on device.
"""

from __future__ import annotations

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow


def synth_video(stream, n_sequences, frames_per_seq, hw=24):
    """(n_sequences*frames_per_seq, hw, hw, 1) float32 frames in [-1, 1].

    Each sequence: a gaussian blob travels start→end across a fixed
    per-sequence background texture; frame order is preserved so the
    dataset has real temporal structure.
    """
    n = n_sequences * frames_per_seq
    t = numpy.tile(numpy.linspace(0.0, 1.0, frames_per_seq),
                   n_sequences)                       # (n,) progress
    start = stream.uniform(hw * 0.15, hw * 0.85, (n_sequences, 2))
    end = stream.uniform(hw * 0.15, hw * 0.85, (n_sequences, 2))
    t_seq = t.reshape(n_sequences, frames_per_seq, 1)
    pos = (start[:, None] * (1 - t_seq)
           + end[:, None] * t_seq).reshape(n, 2)
    background = stream.normal(0.0, 0.08,
                               (n_sequences, hw, hw)).astype(numpy.float32)
    background = numpy.repeat(background, frames_per_seq, axis=0)
    ys, xs = numpy.mgrid[0:hw, 0:hw].astype(numpy.float32)
    d2 = ((xs[None] - pos[:, 0, None, None]) ** 2
          + (ys[None] - pos[:, 1, None, None]) ** 2)
    frames = numpy.exp(-d2 / (2.0 * 2.0 ** 2)) + background
    frames = numpy.clip(frames, 0.0, 1.0) * 2.0 - 1.0
    return frames[..., None].astype(numpy.float32)


class VideoAELoader(FullBatchLoader):
    """Synthetic video frames (stream "video_synth"); targets = inputs."""

    def __init__(self, workflow, n_train=1600, n_valid=400,
                 frames_per_seq=8, hw=24, **kwargs):
        super().__init__(workflow, **kwargs)
        if n_train % frames_per_seq or n_valid % frames_per_seq:
            raise ValueError("set sizes must be whole sequences")
        self.n_train = n_train
        self.n_valid = n_valid
        self.frames_per_seq = frames_per_seq
        self.hw = hw

    def load_data(self):
        stream = prng.get("video_synth", pinned=True)
        total_seqs = (self.n_train + self.n_valid) // self.frames_per_seq
        frames = synth_video(stream, total_seqs, self.frames_per_seq,
                             hw=self.hw)
        self.original_data.reset(frames)
        # labels unused by the MSE evaluator; sequence ids keep the
        # bookkeeping meaningful (e.g. image_saver dumps)
        seq_ids = numpy.repeat(numpy.arange(total_seqs, dtype=numpy.int32),
                               self.frames_per_seq)
        self.original_labels.reset(seq_ids)
        self.class_lengths = [0, self.n_valid, self.n_train]
        self.info("generated %d frames (%d sequences of %d, %dx%d)",
                  len(frames), total_seqs, self.frames_per_seq,
                  self.hw, self.hw)


class VideoAEWorkflow(StandardWorkflow):
    """conv(tanh) → avg_pool ∥ depool → deconv, MSE on the input frame."""


def default_config():
    root.video_ae.defaults({
        "loader": {"minibatch_size": 100, "n_train": 1600, "n_valid": 400},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
        "layers": [
            {"type": "conv_tanh", "n_kernels": 12, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 1e-5, "momentum": 0.9},
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "depooling", "kx": 2, "ky": 2},
            {"type": "deconv", "n_kernels": 1, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 1e-5, "momentum": 0.9},
        ],
    })
    return root.video_ae


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("video_ae", VideoAEWorkflow, VideoAELoader,
                                default_config, loss_function="mse")
