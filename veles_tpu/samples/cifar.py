"""CIFAR-10 small-conv sample — BASELINE.json config[1].

Ref: veles/znicz/samples/CIFAR10/cifar.py [H] (SURVEY §2.3 samples): conv +
pooling + fully-connected topology over 32x32x3 images.

Data: real CIFAR-10 python-pickle batches are used when found; otherwise a
deterministic synthetic stand-in (class prototypes + noise, stream
"cifar_synth") keeps the sample and tests hermetic.
"""

from __future__ import annotations

import os
import pickle

import numpy

from veles_tpu import prng
from veles_tpu.config import root, get
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow


class CifarLoader(FullBatchLoader):
    """CIFAR-10 (or synthetic stand-in), NHWC float32 in [-1, 1]."""

    def __init__(self, workflow, n_train=50000, n_valid=10000,
                 data_dir=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.data_dir = data_dir

    def _dataset_dir(self):
        if self.data_dir:
            return self.data_dir
        configured = get(root.common.dirs.datasets)
        if configured:
            return os.path.join(configured, "cifar-10-batches-py")
        env = os.environ.get("VELES_DATASETS")
        return (os.path.join(env, "cifar-10-batches-py") if env else None)

    def load_data(self):
        data_dir = self._dataset_dir()
        if data_dir and os.path.exists(os.path.join(data_dir, "data_batch_1")):
            self._load_real(data_dir)
        else:
            self._load_synthetic()

    @staticmethod
    def _read_batch(path):
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return data, numpy.array(d[b"labels"], numpy.int32)

    def _load_real(self, data_dir):
        xs, ys = [], []
        for i in range(1, 6):
            x, y = self._read_batch(os.path.join(data_dir,
                                                 "data_batch_%d" % i))
            xs.append(x)
            ys.append(y)
        train_x = numpy.concatenate(xs)[:self.n_train]
        train_y = numpy.concatenate(ys)[:self.n_train]
        test_x, test_y = self._read_batch(os.path.join(data_dir,
                                                       "test_batch"))
        test_x, test_y = test_x[:self.n_valid], test_y[:self.n_valid]
        data = numpy.concatenate([test_x, train_x])
        labels = numpy.concatenate([test_y, train_y])
        self.original_data.reset(
            (data.astype(numpy.float32) / 127.5) - 1.0)
        self.original_labels.reset(labels.astype(numpy.int32))
        self.class_lengths = [0, len(test_x), len(train_x)]
        self.info("loaded real CIFAR-10 from %s", data_dir)

    def _load_synthetic(self):
        stream = prng.get("cifar_synth", pinned=True)
        total = self.n_train + self.n_valid
        protos = stream.uniform(-1.0, 1.0, (10, 32, 32, 3)).astype(
            numpy.float32)
        labels = numpy.arange(total, dtype=numpy.int32) % 10
        stream.shuffle(labels)
        noise = stream.normal(0.0, 0.6, (total, 32, 32, 3)).astype(
            numpy.float32)
        self.original_data.reset(protos[labels] + noise)
        self.original_labels.reset(labels)
        self.class_lengths = [0, self.n_valid, self.n_train]
        self.info("generated synthetic CIFAR-shaped data (%d train / %d "
                  "valid)", self.n_train, self.n_valid)


class CifarWorkflow(StandardWorkflow):
    """Small conv net (ref sample topology class)."""


def default_config():
    root.cifar.defaults({
        "loader": {"minibatch_size": 100, "n_train": 50000,
                   "n_valid": 10000},
        "decision": {"max_epochs": 20, "fail_iterations": 100},
        # strict-relu convs with explicit gaussian init, caffe-style — the
        # reference's cifar configs pinned weights_filling/stddev the same
        # way; the smooth-relu glorot default stalls at chance on this
        # depth (tests/test_samples_real_data.py documents the contrast)
        "layers": [
            {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 64, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
    })
    return root.cifar


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("cifar", CifarWorkflow, CifarLoader,
                                default_config)
