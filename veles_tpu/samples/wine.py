"""Wine — the hello-world FC sample.

Ref: veles/znicz/samples/Wine/wine.py [H] (SURVEY §2.3): the UCI Wine
dataset (178 samples × 13 chemical features, 3 cultivars), a tiny
all2all_tanh(8) → softmax(3) net; the reference's smoke-test sample.

Data: the real ``wine.data`` CSV is used when found under the datasets dir;
otherwise a deterministic synthetic 3-cluster stand-in with the same
shape/scale is generated (this container ships no datasets).
"""

from __future__ import annotations

import os

import numpy

from veles_tpu import prng
from veles_tpu.config import root, get
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow


class WineLoader(FullBatchLoader):
    """(178, 13) features in 3 classes; linear-normalized to [-1, 1]."""

    def __init__(self, workflow, data_path=None, validation_ratio=0.15,
                 **kwargs):
        kwargs.setdefault("normalization_type", "linear")
        super().__init__(workflow, **kwargs)
        self.data_path = data_path
        self.validation_ratio = validation_ratio

    def _find_csv(self):
        if self.data_path:
            return self.data_path
        configured = get(root.common.dirs.datasets)
        for base in (configured, os.environ.get("VELES_DATASETS")):
            if base:
                path = os.path.join(base, "wine", "wine.data")
                if os.path.exists(path):
                    return path
        return None

    def load_data(self):
        path = self._find_csv()
        if path and os.path.exists(path):
            raw = numpy.loadtxt(path, delimiter=",", dtype=numpy.float32)
            labels = raw[:, 0].astype(numpy.int32) - 1   # classes are 1..3
            data = raw[:, 1:]
            self.info("loaded real wine data from %s", path)
        else:
            stream = prng.get("wine_synth", pinned=True)
            n, features = 178, 13
            labels = numpy.arange(n, dtype=numpy.int32) % 3
            stream.shuffle(labels)
            centers = stream.uniform(-2.0, 2.0, (3, features))
            scales = stream.uniform(0.5, 3.0, (1, features))
            data = ((centers[labels] +
                     stream.normal(0.0, 0.6, (n, features))) *
                    scales).astype(numpy.float32)
            self.info("generated synthetic wine-shaped data")
        # deterministic strided validation split, layout [test|valid|train]
        idx = numpy.arange(len(data))
        if self.validation_ratio > 0:
            valid = idx[::int(round(1.0 / self.validation_ratio))]
        else:
            valid = idx[:0]
        train = numpy.setdiff1d(idx, valid)
        order = numpy.concatenate([valid, train])
        self.original_data.reset(data[order])
        self.original_labels.reset(labels[order])
        self.class_lengths = [0, len(valid), len(train)]


class WineWorkflow(StandardWorkflow):
    """13 → 8 tanh → 3 softmax (ref sample topology)."""


def default_config():
    root.wine.defaults({
        "loader": {"minibatch_size": 10},
        "decision": {"max_epochs": 100, "fail_iterations": 30},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 8,
             "learning_rate": 0.5, "momentum": 0.0},
            {"type": "softmax", "output_sample_shape": 3,
             "learning_rate": 0.5, "momentum": 0.0},
        ],
    })
    return root.wine


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("wine", WineWorkflow, WineLoader,
                                default_config)
