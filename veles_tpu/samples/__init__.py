"""Sample launchers — the model zoo (ref: veles/znicz/samples/** [H]).

Each sample module defines a Workflow subclass plus a ``run(load, main)``
entry point called by the CLI (ref convention: SURVEY §3.1), and a direct
``train(...)`` helper usable from code and tests.
"""
