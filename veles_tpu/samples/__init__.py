"""Sample launchers — the model zoo (ref: veles/znicz/samples/** [H]).

Each sample module defines a Workflow subclass plus a ``run(load, main)``
entry point called by the CLI (ref convention: SURVEY §3.1), and a direct
``train(...)`` helper usable from code and tests.  The shared config→workflow
wiring lives in :func:`make_sample`.
"""

from veles_tpu.config import root, get


def run_sample(module, seed=None, build_kwargs=None):
    """Drive one sample's ``run(load, main)`` to completion and return the
    trained workflow.  The standard one-shot runner genetics and ensemble
    share: optional full PRNG reseed, then build + initialize + run."""
    from veles_tpu import prng
    if seed is not None:
        prng.reset()
        prng.seed_all(seed)
    holder = {}

    def load(workflow_cls, **kwargs):
        kwargs.update(build_kwargs or {})
        wf = workflow_cls(None, **kwargs)
        holder["wf"] = wf
        return wf

    def main():
        holder["wf"].initialize()
        holder["wf"].run()

    module.run(load, main)
    return holder["wf"]


def make_sample(config_name, workflow_cls, loader_cls, default_config,
                loss_function="softmax"):
    """Standard sample scaffolding: returns (build, train, run).

    ``config_name`` is the node under ``root`` (e.g. "mnist");
    ``default_config()`` installs defaults (with defaults() semantics so user
    config set beforehand wins).
    """

    def _config():
        cfg = getattr(root, config_name)
        if "layers" not in cfg:
            default_config()
            cfg = getattr(root, config_name)
        return cfg

    def _workflow_kwargs():
        """The ONE cfg→constructor-kwargs assembly (build and run share it)."""
        cfg = _config()
        kwargs = dict(
            name=config_name,
            loader_factory=loader_cls,
            loader_config={k: get(v, v) for k, v in cfg.loader.items()},
            layers=get(cfg.layers, cfg.layers),
            decision_config={k: get(v, v) for k, v in cfg.decision.items()},
            loss_function=loss_function)
        if "snapshotter" in cfg:
            kwargs["snapshotter_config"] = {
                k: get(v, v) for k, v in cfg.snapshotter.items()}
        if "grad_accum" in cfg:
            # config/CLI-reachable microbatching, e.g.
            # ``root.mnist.grad_accum=4`` (see FusedRunner.grad_accum)
            kwargs["grad_accum"] = int(get(cfg.grad_accum,
                                           cfg.grad_accum))
        return kwargs

    def build(fused=True, **overrides):
        kwargs = _workflow_kwargs()
        kwargs["loader_config"].update(overrides.pop("loader", {}))
        kwargs["decision_config"].update(overrides.pop("decision", {}))
        kwargs.update(overrides)
        return workflow_cls(None, fused=fused, **kwargs)

    def train(fused=True, **overrides):
        wf = build(fused=fused, **overrides)
        wf.initialize()
        wf.run()
        return wf

    def run(load, main):
        load(workflow_cls, **_workflow_kwargs())
        main()

    return build, train, run


def make_trainer_sample(config_name, workflow_cls, default_config,
                        sections=("loader", "trainer", "decision")):
    """Scaffolding for non-StandardWorkflow samples (Kohonen, RBM): the
    workflow constructor takes one ``<section>_config`` dict per section."""

    def _workflow_kwargs():
        default_config()
        cfg = getattr(root, config_name)
        kwargs = {"name": config_name}
        for section in sections:
            kwargs["%s_config" % section] = {
                k: get(v, v) for k, v in getattr(cfg, section).items()}
        return kwargs

    def build(**overrides):
        kwargs = _workflow_kwargs()
        for section in sections:
            kwargs["%s_config" % section].update(
                overrides.pop(section, {}))
        kwargs.update(overrides)
        return workflow_cls(None, **kwargs)

    def train(**overrides):
        wf = build(**overrides)
        wf.initialize()
        wf.run()
        return wf

    def run(load, main):
        load(workflow_cls, **_workflow_kwargs())
        main()

    return build, train, run
