"""MNIST convolutional sample — the reference's deep MNIST variant.

Ref: veles/znicz/samples/MNIST/mnist_conv.py(-ish) [M] (SURVEY §2.3 samples
row): conv + pooling LeNet-style topology over 28x28x1 MNIST images,
sharing :class:`veles_tpu.samples.mnist.MnistLoader` (real IDX files when
present, hermetic synthetic stand-in otherwise) in its NHWC layout.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.samples.mnist import MnistLoader
from veles_tpu.standard_workflow import StandardWorkflow


class MnistConvLoader(MnistLoader):
    """MNIST in the conv layout (N, 28, 28, 1)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("sample_shape", (28, 28, 1))
        super().__init__(workflow, **kwargs)


class MnistConvWorkflow(StandardWorkflow):
    """28x28x1 → conv32 → pool → conv64 → pool → 100 tanh → 10 softmax."""


def default_config():
    root.mnist_conv.defaults({
        "loader": {"minibatch_size": 100, "n_train": 60000,
                   "n_valid": 10000},
        "decision": {"max_epochs": 10, "fail_iterations": 50},
        # strict-relu convs with explicit gaussian init: the reference's
        # conv sample configs pinned weights_filling/stddev the same way
        # (the smooth-relu default init trains an order of magnitude
        # slower on this topology)
        "layers": [
            {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 64, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "all2all_tanh", "output_sample_shape": 100,
             "learning_rate": 0.02, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
    })
    return root.mnist_conv


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("mnist_conv", MnistConvWorkflow,
                                MnistConvLoader, default_config)
