"""Directory-image-dataset sample: train a small conv net on a directory of
images, one class per subdirectory.

Ref: the reference's file-image sample pipelines (veles/loader/file_image.py
driven samples [M], SURVEY §2.2/§2.3): point the framework at a directory
tree and train — no dataset-specific code.  Uses
:class:`veles_tpu.loader.image.AutoSplitImageLoader` (PIL decode, scale,
deterministic validation split) end to end.

Config (``root.image_dir``): ``loader.directory`` is required; the softmax
width follows the classes actually containing images, discovered at build
time.
"""

from __future__ import annotations

from veles_tpu.config import root, get
from veles_tpu.loader.image import AutoSplitImageLoader, scan_directory
from veles_tpu.standard_workflow import StandardWorkflow


class ImageDirWorkflow(StandardWorkflow):
    """scale→conv→pool→conv→pool→FC over a scanned image directory."""


def default_config():
    root.image_dir.defaults({
        "loader": {"minibatch_size": 32, "scale": (32, 32),
                   "validation_ratio": 0.2, "color_space": "RGB"},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
        # strict-relu convs with explicit init (see samples/mnist_conv.py)
        "layers": [
            {"type": "conv_str", "n_kernels": 16, "kx": 3, "ky": 3,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 32, "kx": 3, "ky": 3,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
    })
    return root.image_dir


def _workflow_kwargs(loader_overrides=None, decision_overrides=None):
    """The one cfg→constructor-kwargs assembly (build and run share it,
    mirroring make_sample; hand-rolled only because the softmax width is
    discovered from the directory)."""
    cfg = default_config()
    loader_config = {k: get(v, v) for k, v in cfg.loader.items()}
    loader_config.update(loader_overrides or {})
    if "directory" not in loader_config:
        raise ValueError("image_dir sample needs loader.directory "
                         "(root.image_dir.loader.directory=PATH)")
    decision_config = {k: get(v, v) for k, v in cfg.decision.items()}
    decision_config.update(decision_overrides or {})
    layers = [dict(layer) for layer in get(cfg.layers, cfg.layers)]
    # count only classes that actually CONTAIN images — the loader derives
    # its label map the same way, so the widths always agree
    _, names = scan_directory(loader_config["directory"])
    layers[-1]["output_sample_shape"] = max(2, len(set(names)))
    kwargs = dict(name="image_dir", loader_factory=AutoSplitImageLoader,
                  loader_config=loader_config, layers=layers,
                  decision_config=decision_config, loss_function="softmax")
    if "snapshotter" in cfg:
        kwargs["snapshotter_config"] = {
            k: get(v, v) for k, v in cfg.snapshotter.items()}
    return kwargs


def build(fused=True, **overrides):
    kwargs = _workflow_kwargs(overrides.pop("loader", None),
                              overrides.pop("decision", None))
    kwargs.update(overrides)  # layers / loss_function / name override clean
    return ImageDirWorkflow(None, fused=fused, **kwargs)


def train(fused=True, **overrides):
    wf = build(fused=fused, **overrides)
    wf.initialize()
    wf.run()
    return wf


def run(load, main):
    load(ImageDirWorkflow, **_workflow_kwargs())
    main()
