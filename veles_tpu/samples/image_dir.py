"""Directory-image-dataset sample: train a small conv net on a directory of
images, one class per subdirectory.

Ref: the reference's file-image sample pipelines (veles/loader/file_image.py
driven samples [M], SURVEY §2.2/§2.3): point the framework at a directory
tree and train — no dataset-specific code.  Uses
:class:`veles_tpu.loader.image.AutoSplitImageLoader` (PIL decode, scale,
deterministic validation split) end to end.

Config (``root.image_dir``): ``loader.directory`` is required; class count
is discovered from the subdirectories at load time, so
``layers[-1].output_sample_shape`` must match (or use :func:`build` which
patches it automatically).
"""

from __future__ import annotations

from veles_tpu.config import root, get
from veles_tpu.loader.image import AutoSplitImageLoader
from veles_tpu.standard_workflow import StandardWorkflow


class ImageDirWorkflow(StandardWorkflow):
    """scale→conv→pool→conv→pool→FC over a scanned image directory."""


def default_config():
    root.image_dir.defaults({
        "loader": {"minibatch_size": 32, "scale": (32, 32),
                   "validation_ratio": 0.2, "color_space": "RGB"},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
        # strict-relu convs with explicit init (see samples/mnist_conv.py)
        "layers": [
            {"type": "conv_str", "n_kernels": 16, "kx": 3, "ky": 3,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 32, "kx": 3, "ky": 3,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 2,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
    })
    return root.image_dir


def _n_classes(directory):
    import os
    return max(2, len([d for d in os.listdir(directory)
                       if os.path.isdir(os.path.join(directory, d))]))


def build(fused=True, **overrides):
    cfg = default_config()
    loader_config = {k: get(v, v) for k, v in cfg.loader.items()}
    loader_config.update(overrides.pop("loader", {}))
    if "directory" not in loader_config:
        raise ValueError("image_dir sample needs loader.directory "
                         "(root.image_dir.loader.directory=PATH)")
    decision_config = {k: get(v, v) for k, v in cfg.decision.items()}
    decision_config.update(overrides.pop("decision", {}))
    layers = [dict(layer) for layer in get(cfg.layers, cfg.layers)]
    # the output layer's width follows the scanned class count
    layers[-1]["output_sample_shape"] = _n_classes(
        loader_config["directory"])
    return ImageDirWorkflow(
        None, name="image_dir", loader_factory=AutoSplitImageLoader,
        loader_config=loader_config, layers=layers,
        decision_config=decision_config, loss_function="softmax",
        fused=fused, **overrides)


def train(fused=True, **overrides):
    wf = build(fused=fused, **overrides)
    wf.initialize()
    wf.run()
    return wf


def run(load, main):
    cfg = default_config()
    loader_config = {k: get(v, v) for k, v in cfg.loader.items()}
    if "directory" not in loader_config:
        raise ValueError("set root.image_dir.loader.directory=PATH")
    layers = [dict(layer) for layer in get(cfg.layers, cfg.layers)]
    layers[-1]["output_sample_shape"] = _n_classes(
        loader_config["directory"])
    load(ImageDirWorkflow, name="image_dir",
         loader_factory=AutoSplitImageLoader, loader_config=loader_config,
         layers=layers,
         decision_config={k: get(v, v) for k, v in cfg.decision.items()},
         loss_function="softmax")
    main()
