"""MNIST fully-connected sample — BASELINE.json config[0].

Ref: veles/znicz/samples/MNIST/mnist.py [H]: 784→100(tanh)→10(softmax), the
canonical end-to-end slice (SURVEY §7 stage 2).

Data: real MNIST IDX files are used when found (``data_dir`` config,
``root.common.dirs.datasets``, or $VELES_DATASETS); otherwise a deterministic
synthetic MNIST-shaped dataset is generated from the named PRNG stream
"mnist_synth" (class prototypes + gaussian noise) so the sample and its
convergence tests run hermetically — this container has no datasets and no
network.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy

from veles_tpu import prng
from veles_tpu.config import root, get
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return numpy.frombuffer(f.read(), numpy.uint8).reshape(shape)


def _find_idx(data_dir, stem):
    for suffix in ("", ".gz"):
        path = os.path.join(data_dir, stem + suffix)
        if os.path.exists(path):
            return path
    return None


class MnistLoader(FullBatchLoader):
    """MNIST (or synthetic stand-in) in [-1, 1].

    ``sample_shape`` picks the layout: (784,) flat for the FC sample
    (default), (28, 28, 1) NHWC for the conv sample.
    """

    def __init__(self, workflow, n_train=60000, n_valid=10000,
                 data_dir=None, sample_shape=(784,), **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.data_dir = data_dir
        self.sample_shape = tuple(sample_shape)

    def _dataset_dir(self):
        if self.data_dir:
            return self.data_dir
        configured = get(root.common.dirs.datasets)
        if configured:
            return os.path.join(configured, "mnist")
        env = os.environ.get("VELES_DATASETS")
        return os.path.join(env, "mnist") if env else None

    def load_data(self):
        data_dir = self._dataset_dir()
        if data_dir and _find_idx(data_dir, "train-images-idx3-ubyte"):
            self._load_real(data_dir)
        else:
            self._load_synthetic()

    def _load_real(self, data_dir):
        train_x = _read_idx(_find_idx(data_dir, "train-images-idx3-ubyte"))
        train_y = _read_idx(_find_idx(data_dir, "train-labels-idx1-ubyte"))
        test_x = _read_idx(_find_idx(data_dir, "t10k-images-idx3-ubyte"))
        test_y = _read_idx(_find_idx(data_dir, "t10k-labels-idx1-ubyte"))
        n_train = min(self.n_train, len(train_x))
        n_valid = min(self.n_valid, len(test_x))
        # layout [test | validation | train]: MNIST's 10k set is validation
        data = numpy.concatenate([test_x[:n_valid], train_x[:n_train]])
        labels = numpy.concatenate([test_y[:n_valid], train_y[:n_train]])
        self.original_data.reset(
            (data.astype(numpy.float32) / 127.5 - 1.0)
            .reshape((len(data),) + self.sample_shape))
        self.original_labels.reset(labels.astype(numpy.int32))
        self.class_lengths = [0, n_valid, n_train]
        self.info("loaded real MNIST from %s (%d train / %d valid)",
                  data_dir, n_train, n_valid)

    def _load_synthetic(self):
        stream = prng.get("mnist_synth", pinned=True)
        n_train, n_valid = self.n_train, self.n_valid
        total = n_train + n_valid
        protos = stream.uniform(-1.0, 1.0, (10, 784)).astype(numpy.float32)
        labels = numpy.arange(total, dtype=numpy.int32) % 10
        stream.shuffle(labels)
        noise = stream.normal(0.0, 0.8, (total, 784)).astype(numpy.float32)
        data = protos[labels] + noise
        # layout [test | validation | train]
        self.original_data.reset(
            data.reshape((total,) + self.sample_shape))
        self.original_labels.reset(labels)
        self.class_lengths = [0, n_valid, n_train]
        self.info("generated synthetic MNIST-shaped data "
                  "(%d train / %d valid)", n_train, n_valid)


class MnistWorkflow(StandardWorkflow):
    """784 → 100 tanh → 10 softmax (ref sample topology)."""


def default_config():
    """Install the sample's defaults into ``root.mnist`` (config-file role,
    ref: veles/znicz/samples/MNIST/mnist_config.py [H])."""
    root.mnist.defaults({
        "loader": {"minibatch_size": 100, "n_train": 60000, "n_valid": 10000},
        "decision": {"max_epochs": 10, "fail_iterations": 50},
        "layers": [
            {"type": "all2all_tanh", "output_sample_shape": 100,
             "learning_rate": 0.03, "momentum": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.03, "momentum": 0.9},
        ],
    })
    return root.mnist


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("mnist", MnistWorkflow, MnistLoader,
                                default_config)
