"""CIFAR-10 residual conv net — the `residual` layer type in the zoo.

Beyond parity: the reference's samples are all linear chains (ref:
veles/znicz/samples/CIFAR10/cifar.py [H] is the closest topology); this
sample stacks two ResNet-style identity blocks (conv-conv-add, SAME
padding keeps shapes skip-compatible) on the same CIFAR loader, showing
the fused engine's DAG support end to end — config, training,
epoch-scan, snapshots and serving all ride the standard machinery.

Run: ``python -m veles_tpu veles_tpu/samples/cifar_resnet.py``
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.samples.cifar import CifarLoader
from veles_tpu.standard_workflow import StandardWorkflow


class CifarResNetWorkflow(StandardWorkflow):
    """Small residual conv net (two identity blocks)."""


def _conv(channels, lr, stride=1):
    return {"type": "conv_str", "n_kernels": channels, "kx": 3, "ky": 3,
            "sliding": stride, "padding": "SAME", "learning_rate": lr,
            "momentum": 0.9, "weights_filling": "gaussian",
            "weights_stddev": 0.05}


def _block(channels, lr):
    """conv -> conv -> add-input: one identity residual block."""
    return [_conv(channels, lr), _conv(channels, lr),
            {"type": "residual", "skip": 2}]


def _down_block(channels, lr):
    """Downsampling block: the main path strides 2 and widens; the skip
    path is a 1x1/stride-2 projection (`residual_proj`)."""
    return [_conv(channels, lr, stride=2), _conv(channels, lr),
            {"type": "residual_proj", "skip": 2, "n_kernels": channels,
             "sliding": 2, "learning_rate": lr, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05}]


def default_config():
    lr = 0.02
    root.cifar_resnet.defaults({
        "loader": {"minibatch_size": 100, "n_train": 50000,
                   "n_valid": 10000},
        "decision": {"max_epochs": 20, "fail_iterations": 100},
        "layers": [
            # stem sets the channel width the identity blocks preserve
            {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": lr, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            *_block(32, lr),
            *_down_block(64, lr),      # 16x16x32 -> 8x8x64, projected skip
            *_block(64, lr),
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": lr, "momentum": 0.9},
        ],
    })
    return root.cifar_resnet


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("cifar_resnet", CifarResNetWorkflow,
                                CifarLoader, default_config)
