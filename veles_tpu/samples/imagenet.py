"""ImageNet AlexNet-class sample — BASELINE.json configs[2] and [4].

Ref: veles/znicz/samples/imagenet/ [M] (SURVEY §2.3): the AlexNet-era
pipeline — mean-subtracted 256×256 images, random 227-crop + mirror, five
conv blocks with LRN and max-pooling, two dropout-FC layers, softmax-1000.

TPU-native shape: augmentation is a stochastic layer inside the jitted step
(ops/augmentation.py), data comes from a record file (loader/records.py,
memmap — the LMDB role) or a synthetic stand-in, and multi-chip runs shard
the batch axis over the mesh via ``veles_tpu.parallel.ShardedTrainer``
(BASELINE config[4]'s distributed ImageNet: the gradient all-reduce rides
ICI instead of master–slave ZeroMQ — SURVEY §2.5).
"""

from __future__ import annotations

import os

import numpy

from veles_tpu import prng
from veles_tpu.config import root, get
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.records import RecordsLoader
from veles_tpu.standard_workflow import StandardWorkflow


def alexnet_layers(n_classes=1000, crop=(227, 227), lr=0.01, momentum=0.9,
                   weight_decay=0.0005):
    """The canonical AlexNet topology as a layers config list."""
    conv = lambda n, k, s, pad, lrn: (  # noqa: E731
        [{"type": "conv_str", "n_kernels": n, "kx": k, "ky": k,
          "sliding": (s, s), "padding": pad, "learning_rate": lr,
          "momentum": momentum, "weight_decay": weight_decay}] +
        ([{"type": "norm"}] if lrn else []))
    fc = lambda n: [  # noqa: E731
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "all2all_str", "output_sample_shape": n,
         "learning_rate": lr, "momentum": momentum,
         "weight_decay": weight_decay}]
    return (
        [{"type": "random_crop_flip", "crop": list(crop)}] +
        conv(96, 11, 4, "VALID", True) +
        [{"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)}] +
        conv(256, 5, 1, "SAME", True) +
        [{"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)}] +
        conv(384, 3, 1, "SAME", False) +
        conv(384, 3, 1, "SAME", False) +
        conv(256, 3, 1, "SAME", False) +
        [{"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)}] +
        fc(4096) + fc(4096) +
        [{"type": "softmax", "output_sample_shape": n_classes,
          "learning_rate": lr, "momentum": momentum,
          "weight_decay": weight_decay}])


def tiny_layers(n_classes=10, crop=(28, 28), lr=0.01, momentum=0.9):
    """Scaled-down AlexNet shape (same block structure) for tests/CI."""
    return (
        [{"type": "random_crop_flip", "crop": list(crop)}] +
        [{"type": "conv_str", "n_kernels": 16, "kx": 5, "ky": 5,
          "sliding": (2, 2), "padding": "VALID", "learning_rate": lr,
          "momentum": momentum},
         {"type": "norm"},
         {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
         {"type": "conv_str", "n_kernels": 32, "kx": 3, "ky": 3,
          "padding": "SAME", "learning_rate": lr, "momentum": momentum},
         {"type": "max_pooling", "kx": 2, "ky": 2},
         {"type": "dropout", "dropout_ratio": 0.5},
         {"type": "all2all_str", "output_sample_shape": 64,
          "learning_rate": lr, "momentum": momentum},
         {"type": "softmax", "output_sample_shape": n_classes,
          "learning_rate": lr, "momentum": momentum}])


class ImagenetRecordsLoader(RecordsLoader):
    """Record-file ImageNet with mean-image subtraction at fill time."""

    def __init__(self, workflow, mean_path=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.mean_path = mean_path
        self._mean = None

    def load_data(self):
        super().load_data()
        if self.mean_path and os.path.exists(self.mean_path):
            self._mean = numpy.load(self.mean_path).astype(numpy.float32)

    def fill_minibatch(self, indices, actual_size):
        super().fill_minibatch(indices, actual_size)
        if self._mean is not None:
            from veles_tpu import native
            self.minibatch_data.reset(native.subtract_mean(
                self.minibatch_data.mem, self._mean))

    def gather_window(self, indices):
        # the streaming epoch-scan stages through this hook: the window
        # must see the SAME mean-subtracted pixels the per-minibatch
        # path feeds, or --stream-window would silently change the model
        batch, labels = super().gather_window(indices)
        if self._mean is not None:
            from veles_tpu import native
            batch = native.subtract_mean(batch, self._mean)
        return batch, labels


class ImagenetSyntheticLoader(FullBatchLoader):
    """Synthetic ImageNet-shaped stand-in (stream "imagenet_synth") so the
    sample and its tests run hermetically; shape/classes configurable."""

    def __init__(self, workflow, n_train=512, n_valid=128, image_hw=(32, 32),
                 n_classes=10, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.image_hw = tuple(image_hw)
        self.n_classes = n_classes

    def load_data(self):
        stream = prng.get("imagenet_synth", pinned=True)
        h, w = self.image_hw
        total = self.n_train + self.n_valid
        protos = stream.uniform(-1.0, 1.0,
                                (self.n_classes, h, w, 3)).astype(
                                    numpy.float32)
        labels = numpy.arange(total, dtype=numpy.int32) % self.n_classes
        stream.shuffle(labels)
        noise = stream.normal(0.0, 0.5, (total, h, w, 3)).astype(
            numpy.float32)
        self.original_data.reset(protos[labels] + noise)
        self.original_labels.reset(labels)
        self.class_lengths = [0, self.n_valid, self.n_train]


def make_loader(workflow, records_path=None, **kwargs):
    """Real records when available, synthetic otherwise (cifar convention)."""
    if records_path and os.path.exists(records_path):
        for synth_only in ("image_hw", "n_classes", "n_train", "n_valid"):
            kwargs.pop(synth_only, None)
        return ImagenetRecordsLoader(workflow, path=records_path, **kwargs)
    kwargs.pop("mean_path", None)
    return ImagenetSyntheticLoader(workflow, **kwargs)


class ImagenetWorkflow(StandardWorkflow):
    """AlexNet-class supervised workflow."""


def default_config():
    # pick the topology by data source: real record file → the full
    # 227×227 1000-class AlexNet; synthetic stand-in → the tiny shape
    # matching its 32×32 images (explicit root.imagenet.layers always wins)
    records = get(root.imagenet.loader.records_path)
    use_full = bool(records) and os.path.exists(records)
    root.imagenet.defaults({
        "loader": {"minibatch_size": 128, "records_path": None,
                   "n_train": 512, "n_valid": 128, "image_hw": (32, 32),
                   "n_classes": 10},
        "decision": {"max_epochs": 10, "fail_iterations": 10},
        "layers": alexnet_layers() if use_full else tiny_layers(),
    })
    return root.imagenet


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("imagenet", ImagenetWorkflow, make_loader,
                                default_config)
