"""Kohonen SOM demo sample — BASELINE.json config[3] (Kohonen part).

Ref: veles/znicz/samples/Kohonen/kohonen.py [H] (SURVEY §2.3 samples):
unsupervised SOM on 2-D point clouds.  The workflow is a NON-SGD training
cycle — Repeater → Loader → KohonenTrainer → KohonenDecision — proving the
graph core is not hardwired to the forward/evaluator/gd shape.
"""

from __future__ import annotations

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.ops.kohonen import (KohonenTrainer, KohonenForward,
                                   KohonenDecision)
from veles_tpu.ops.nn_units import NNWorkflow
from veles_tpu.workflow import Repeater


class KohonenLoader(FullBatchLoader):
    """Synthetic 2-D point cloud: a few Gaussian blobs (stream
    "kohonen_synth"), train-set only — the SOM is unsupervised."""

    def __init__(self, workflow, n_train=2000, n_blobs=5, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_blobs = n_blobs
        self.has_labels = False

    def load_data(self):
        stream = prng.get("kohonen_synth", pinned=True)
        centers = stream.uniform(-1.0, 1.0, (self.n_blobs, 2)).astype(
            numpy.float32)
        which = numpy.arange(self.n_train) % self.n_blobs
        noise = stream.normal(0.0, 0.15, (self.n_train, 2)).astype(
            numpy.float32)
        self.original_data.reset(centers[which] + noise)
        self.class_lengths = [0, 0, self.n_train]


class KohonenWorkflow(NNWorkflow):
    """The unsupervised SOM training cycle."""

    def __init__(self, workflow=None, name=None, loader_config=None,
                 trainer_config=None, decision_config=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = KohonenLoader(self, name="loader",
                                    **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.trainer = KohonenTrainer(self, name="trainer",
                                      **(trainer_config or {}))
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("mask", "minibatch_mask"),
                                "minibatch_class")

        self.decision = KohonenDecision(self, name="decision",
                                        **(decision_config or {}))
        self.decision.link_from(self.trainer)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_number")
        self.decision.link_attrs(self.trainer, "metrics")

        self.forward = KohonenForward(self, name="forward")
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("mask", "minibatch_mask"))
        self.forward.link_attrs(self.trainer, "weights")
        # forward sits OUTSIDE the cycle: it classifies on demand after
        # training (the reference ran it in the evaluation pass / plots)
        self.forward.link_from(self.decision)
        self.forward.gate_skip = ~self.decision.complete

        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.forward)
        self.end_point.gate_block = ~self.decision.complete


def default_config():
    root.kohonen.defaults({
        "loader": {"minibatch_size": 100, "n_train": 2000},
        "trainer": {"shape": (8, 8), "learning_rate": 0.2,
                    "decay_steps": 200},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
    })
    return root.kohonen


from veles_tpu.samples import make_trainer_sample  # noqa: E402

build, train, run = make_trainer_sample("kohonen", KohonenWorkflow,
                                        default_config)
