"""MNIST convolutional autoencoder sample — BASELINE.json config[3] (AE).

Ref: veles/znicz/samples/MnistAE/mnist_ae.py [H] (SURVEY §2.3 samples): a
conv encoder mirrored by depooling + deconv, trained with the MSE evaluator
against the input image itself (the target aliases the loader's
minibatch_data, exactly the reference's wiring).
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.standard_workflow import StandardWorkflow
from veles_tpu.samples.mnist import MnistLoader


class MnistAELoader(MnistLoader):
    """MNIST as NHWC images (N, 28, 28, 1) in [-1, 1] for the conv stack."""

    def load_data(self):
        super().load_data()
        data = self.original_data.mem
        self.original_data.reset(data.reshape(len(data), 28, 28, 1))


class MnistAEWorkflow(StandardWorkflow):
    """conv(tanh) → avg_pool ∥ depool → deconv, MSE on the input."""


def default_config():
    root.mnist_ae.defaults({
        "loader": {"minibatch_size": 100, "n_train": 60000, "n_valid": 10000},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
        "layers": [
            {"type": "conv_tanh", "n_kernels": 16, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.0001, "momentum": 0.9},
            {"type": "avg_pooling", "kx": 2, "ky": 2},
            {"type": "depooling", "kx": 2, "ky": 2},
            {"type": "deconv", "n_kernels": 1, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.0001, "momentum": 0.9},
        ],
    })
    return root.mnist_ae


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("mnist_ae", MnistAEWorkflow, MnistAELoader,
                                default_config, loss_function="mse")
