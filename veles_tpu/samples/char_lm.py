"""Character-LM sample — tiny decoder-only transformer (long-context family).

Beyond-parity model family (the reference has no attention — SURVEY §5.7):
trains a causal transformer on synthetic structured sequences (deterministic
cyclic grammar from the "charlm_synth" stream, so loss is provably
reducible), same non-SGD trainer cycle as Kohonen/RBM.
"""

from __future__ import annotations

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.ops.nn_units import NNWorkflow
from veles_tpu.ops.transformer import TransformerTrainer, TransformerDecision
from veles_tpu.workflow import Repeater


class CharSequenceLoader(FullBatchLoader):
    """Token sequences for the LM: REAL TEXT when ``text_path`` points at
    a file (byte-level — every file is its own tokenizer-free corpus,
    vocab 256, split into overlapping seq_len windows, last 1/8 of the
    FILE held out as validation so the split is by position, not by
    window shuffle), synthetic otherwise: each synthetic sequence cycles
    an arithmetic pattern ``t[i+1] = (t[i] + step) % vocab`` whose step
    is sampled per sequence — a 1-layer model can learn it (loss
    provably reducible, hermetic CI)."""

    def __init__(self, workflow, n_train=512, n_valid=128, seq_len=64,
                 vocab=32, text_path=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.seq_len = seq_len
        self.vocab = vocab
        #: optional real corpus (any file, read as bytes)
        self.text_path = text_path
        self.has_labels = False

    def _load_text(self):
        import os
        raw = numpy.fromfile(self.text_path, numpy.uint8)
        if len(raw) < 2 * self.seq_len:
            raise ValueError("%s: %d bytes < 2 windows of seq_len %d"
                             % (self.text_path, len(raw), self.seq_len))
        self.vocab = 256
        split = len(raw) - max(len(raw) // 8, self.seq_len)

        def windows(chunk, cap):
            # stride spreads the cap across the WHOLE chunk (a large
            # corpus contributes windows from everywhere, not just its
            # first cap·stride bytes), overlapping when the chunk is
            # small
            span = len(chunk) - self.seq_len
            n = min(max(span // max(self.seq_len // 2, 1) + 1, 1), cap)
            stride = max(span // max(n - 1, 1), 1) if n > 1 else 1
            return numpy.stack([
                chunk[i * stride:i * stride + self.seq_len]
                for i in range(n)])

        train = windows(raw[:split], self.n_train)
        valid = windows(raw[split:], self.n_valid)
        self.original_data.reset(numpy.concatenate(
            [valid, train]).astype(numpy.int32))
        self.class_lengths = [0, len(valid), len(train)]
        self.info("text corpus %s: %d bytes -> %d train / %d valid "
                  "windows of %d (byte-level vocab 256)",
                  os.path.basename(str(self.text_path)), len(raw),
                  len(train), len(valid), self.seq_len)

    def load_data(self):
        import os
        if self.text_path:
            # an EXPLICIT corpus path must never fall back silently — a
            # typo would train to convergence on synthetic data while
            # the user believes the metrics are for their corpus
            if not os.path.exists(str(self.text_path)):
                raise FileNotFoundError(
                    "char_lm text_path %r does not exist"
                    % (self.text_path,))
            self._load_text()
            return
        stream = prng.get("charlm_synth", pinned=True)
        total = self.n_train + self.n_valid
        starts = stream.randint(0, self.vocab, total)
        steps = stream.randint(1, 5, total)
        idx = numpy.arange(self.seq_len)
        data = (starts[:, None] + steps[:, None] * idx[None, :]) % self.vocab
        self.original_data.reset(data.astype(numpy.int32))
        self.class_lengths = [0, self.n_valid, self.n_train]


class CharLMWorkflow(NNWorkflow):
    def __init__(self, workflow=None, name=None, loader_config=None,
                 trainer_config=None, decision_config=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = CharSequenceLoader(self, name="loader",
                                         **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.trainer = TransformerTrainer(self, name="trainer",
                                          **(trainer_config or {}))
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("mask", "minibatch_mask"),
                                "minibatch_class")

        self.decision = TransformerDecision(self, name="decision",
                                            **(decision_config or {}))
        self.decision.link_from(self.trainer)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_number")
        self.decision.link_attrs(self.trainer, "metrics")

        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def default_config():
    # a real text corpus is byte-level: the trainer's vocab must cover
    # every byte, so the default follows the data source (explicit
    # root.char_lm.trainer.vocab always wins; vocab CONSISTENCY between
    # loader and trainer is enforced at trainer.initialize either way).
    # Raw-dict probing: a dotted read would create a phantom Config node
    # that defaults() then refuses to overwrite.
    loader_node = root.char_lm.__dict__.get("loader")
    text = (loader_node.__dict__.get("text_path")
            if loader_node is not None else None)
    vocab = 256 if isinstance(text, str) and text else 32
    root.char_lm.defaults({
        "loader": {"minibatch_size": 64, "n_train": 512, "n_valid": 128,
                   "seq_len": 64, "vocab": vocab, "text_path": None},
        "trainer": {"vocab": vocab, "d_model": 64, "n_heads": 4,
                    "n_layers": 2, "max_len": 64, "learning_rate": 1e-3},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
    })
    return root.char_lm


from veles_tpu.samples import make_trainer_sample  # noqa: E402

build, train, run = make_trainer_sample("char_lm", CharLMWorkflow,
                                        default_config)


def sample_tokens(wf, prompt, n_new=32, temperature=0.0, seed=0):
    """Continue token sequences with the trained model — KV-cached
    autoregressive decoding, greedy by default.  ``prompt``:
    (batch, s) ints; returns (batch, s + n_new) numpy int32.  Thin
    wrapper over ops.transformer.trainer_sample_tokens (the shared
    decode entry point, pipelined-trainer safe)."""
    from veles_tpu.ops.transformer import trainer_sample_tokens
    return trainer_sample_tokens(wf.trainer, prompt, n_new=n_new,
                                 temperature=temperature, seed=seed)
