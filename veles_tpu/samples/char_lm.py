"""Character-LM sample — tiny decoder-only transformer (long-context family).

Beyond-parity model family (the reference has no attention — SURVEY §5.7):
trains a causal transformer on synthetic structured sequences (deterministic
cyclic grammar from the "charlm_synth" stream, so loss is provably
reducible), same non-SGD trainer cycle as Kohonen/RBM.
"""

from __future__ import annotations

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.ops.nn_units import NNWorkflow
from veles_tpu.ops.transformer import TransformerTrainer, TransformerDecision
from veles_tpu.workflow import Repeater


class CharSequenceLoader(FullBatchLoader):
    """Synthetic token sequences with predictable structure: each sequence
    cycles an arithmetic pattern ``t[i+1] = (t[i] + step) % vocab`` whose
    step is sampled per sequence — a 1-layer model can learn it."""

    def __init__(self, workflow, n_train=512, n_valid=128, seq_len=64,
                 vocab=32, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.seq_len = seq_len
        self.vocab = vocab
        self.has_labels = False

    def load_data(self):
        stream = prng.get("charlm_synth", pinned=True)
        total = self.n_train + self.n_valid
        starts = stream.randint(0, self.vocab, total)
        steps = stream.randint(1, 5, total)
        idx = numpy.arange(self.seq_len)
        data = (starts[:, None] + steps[:, None] * idx[None, :]) % self.vocab
        self.original_data.reset(data.astype(numpy.int32))
        self.class_lengths = [0, self.n_valid, self.n_train]


class CharLMWorkflow(NNWorkflow):
    def __init__(self, workflow=None, name=None, loader_config=None,
                 trainer_config=None, decision_config=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = CharSequenceLoader(self, name="loader",
                                         **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.trainer = TransformerTrainer(self, name="trainer",
                                          **(trainer_config or {}))
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("mask", "minibatch_mask"),
                                "minibatch_class")

        self.decision = TransformerDecision(self, name="decision",
                                            **(decision_config or {}))
        self.decision.link_from(self.trainer)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_number")
        self.decision.link_attrs(self.trainer, "metrics")

        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def default_config():
    root.char_lm.defaults({
        "loader": {"minibatch_size": 64, "n_train": 512, "n_valid": 128,
                   "seq_len": 64, "vocab": 32},
        "trainer": {"vocab": 32, "d_model": 64, "n_heads": 4, "n_layers": 2,
                    "max_len": 64, "learning_rate": 1e-3},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
    })
    return root.char_lm


from veles_tpu.samples import make_trainer_sample  # noqa: E402

build, train, run = make_trainer_sample("char_lm", CharLMWorkflow,
                                        default_config)


def sample_tokens(wf, prompt, n_new=32, temperature=0.0, seed=0):
    """Continue token sequences with the trained model — KV-cached
    autoregressive decoding, greedy by default.  ``prompt``:
    (batch, s) ints; returns (batch, s + n_new) numpy int32.  Thin
    wrapper over ops.transformer.trainer_sample_tokens (the shared
    decode entry point, pipelined-trainer safe)."""
    from veles_tpu.ops.transformer import trainer_sample_tokens
    return trainer_sample_tokens(wf.trainer, prompt, n_new=n_new,
                                 temperature=temperature, seed=seed)
