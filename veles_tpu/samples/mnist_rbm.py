"""MNIST RBM sample — CD-1 feature learning on binarized digits.

Ref: veles/znicz samples exercising rbm_units [M] (SURVEY §2.3).  Same
non-SGD cycle shape as the Kohonen sample: Repeater → Loader → RBMTrainer →
RBMDecision.
"""

from __future__ import annotations

from veles_tpu.config import root
from veles_tpu.ops.nn_units import NNWorkflow
from veles_tpu.ops.rbm import RBMTrainer, RBMForward, RBMDecision
from veles_tpu.samples.mnist import MnistLoader
from veles_tpu.workflow import Repeater


class MnistRBMLoader(MnistLoader):
    """MNIST rescaled from [-1, 1] to [0, 1] (Bernoulli probability scale)."""

    def load_data(self):
        super().load_data()
        self.original_data.reset((self.original_data.mem + 1.0) / 2.0)


class MnistRBMWorkflow(NNWorkflow):
    def __init__(self, workflow=None, name=None, loader_config=None,
                 trainer_config=None, decision_config=None, **kwargs):
        super().__init__(workflow, name=name, **kwargs)
        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = MnistRBMLoader(self, name="loader",
                                     **(loader_config or {}))
        self.loader.link_from(self.repeater)

        self.trainer = RBMTrainer(self, name="trainer",
                                  **(trainer_config or {}))
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("mask", "minibatch_mask"),
                                "minibatch_class")

        self.decision = RBMDecision(self, name="decision",
                                    **(decision_config or {}))
        self.decision.link_from(self.trainer)
        self.decision.link_attrs(self.loader, "minibatch_class",
                                 "minibatch_size", "last_minibatch",
                                 "class_lengths", "epoch_number")
        self.decision.link_attrs(self.trainer, "metrics")

        self.forward = RBMForward(self, name="forward")
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"))
        self.forward.link_attrs(self.trainer, "weights", "hbias")
        self.forward.link_from(self.decision)
        self.forward.gate_skip = ~self.decision.complete

        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.forward)
        self.end_point.gate_block = ~self.decision.complete


def default_config():
    root.mnist_rbm.defaults({
        "loader": {"minibatch_size": 100, "n_train": 60000, "n_valid": 0},
        "trainer": {"n_hidden": 256, "learning_rate": 0.05, "cd_k": 1},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
    })
    return root.mnist_rbm


from veles_tpu.samples import make_trainer_sample  # noqa: E402

build, train, run = make_trainer_sample("mnist_rbm", MnistRBMWorkflow,
                                        default_config)
