"""Lines sample — generated geometric images, orientation classification.

Ref: veles/znicz/samples Lines demo (SURVEY §2.3 samples row [H]): the
reference's zoo includes a synthetic "Lines" workflow that classifies
images of straight lines by orientation with a small conv net — the
canonical from-nothing demo that a generated dataset plus the standard
conv stack trains end to end.

TPU-native notes: data is drawn host-side once (vectorized numpy — a
distance-to-line field per sample, no python-per-pixel loops) into a
FullBatchLoader, so the whole train set lives in HBM and the fused step
runs the standard conv topology on the MXU.  Four classes: horizontal,
diagonal (/), vertical, anti-diagonal (\\), each with random center,
angle jitter, thickness, and background noise.
"""

from __future__ import annotations

import numpy

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.standard_workflow import StandardWorkflow

#: class angle centers (radians): 0=horizontal, 1=/, 2=vertical, 3=\
ANGLES = numpy.array([0.0, 0.25, 0.5, 0.75]) * numpy.pi
N_CLASSES = len(ANGLES)


def draw_lines(stream, n, hw=32, jitter=0.12, noise=0.25):
    """(n, hw, hw, 1) float32 images in [-1, 1] + (n,) int32 labels.

    Each image is exp(-d²/2σ²) of the distance field to a random line of
    the class's orientation — fully vectorized over samples and pixels.
    """
    labels = numpy.arange(n, dtype=numpy.int32) % N_CLASSES
    stream.shuffle(labels)
    theta = (ANGLES[labels]
             + stream.uniform(-jitter, jitter, n) * numpy.pi)
    # line through a random interior point, direction (cos t, sin t);
    # normal distance d = |(p - c) · (-sin t, cos t)|
    cx = stream.uniform(hw * 0.3, hw * 0.7, n)
    cy = stream.uniform(hw * 0.3, hw * 0.7, n)
    sigma = stream.uniform(0.6, 1.6, n)
    ys, xs = numpy.mgrid[0:hw, 0:hw].astype(numpy.float32)
    d = ((xs[None] - cx[:, None, None]) * (-numpy.sin(theta))[:, None, None]
         + (ys[None] - cy[:, None, None]) * numpy.cos(theta)[:, None, None])
    img = numpy.exp(-(d * d) / (2.0 * (sigma ** 2)[:, None, None]))
    img += stream.normal(0.0, noise, (n, hw, hw))
    img = numpy.clip(img, 0.0, 1.0) * 2.0 - 1.0
    return img[..., None].astype(numpy.float32), labels


class LinesLoader(FullBatchLoader):
    """Generated line-orientation dataset (stream "lines_synth")."""

    def __init__(self, workflow, n_train=2000, n_valid=500, hw=32,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_train = n_train
        self.n_valid = n_valid
        self.hw = hw

    def load_data(self):
        stream = prng.get("lines_synth", pinned=True)
        total = self.n_train + self.n_valid
        data, labels = draw_lines(stream, total, hw=self.hw)
        self.original_data.reset(data)
        self.original_labels.reset(labels)
        self.class_lengths = [0, self.n_valid, self.n_train]
        self.info("generated %d line images (%dx%d, %d classes)",
                  total, self.hw, self.hw, N_CLASSES)


class LinesWorkflow(StandardWorkflow):
    """Small conv net over generated line images."""


def default_config():
    root.lines.defaults({
        "loader": {"minibatch_size": 100, "n_train": 2000, "n_valid": 500},
        "decision": {"max_epochs": 10, "fail_iterations": 20},
        "layers": [
            {"type": "conv_str", "n_kernels": 16, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "conv_str", "n_kernels": 32, "kx": 5, "ky": 5,
             "padding": "SAME", "learning_rate": 0.02, "momentum": 0.9,
             "weights_filling": "gaussian", "weights_stddev": 0.05},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": N_CLASSES,
             "learning_rate": 0.02, "momentum": 0.9},
        ],
    })
    return root.lines


from veles_tpu.samples import make_sample  # noqa: E402

build, train, run = make_sample("lines", LinesWorkflow, LinesLoader,
                                default_config)
