"""Pipeline parallelism (GPipe) for the uniform transformer block stack.

Beyond-parity capability (the reference scaled by data parallelism only —
SURVEY §2.5): the L decoder blocks are stacked along a leading layer axis,
that axis is sharded over the mesh's ``stage`` axis (each device owns
L/S contiguous blocks), and microbatches stream through the stages with
``lax.ppermute`` hops between neighbors — the classic GPipe schedule
expressed the TPU way: one ``shard_map`` program, activations riding ICI.

The backward pass needs no hand scheduling: `jax.grad` through
``shard_map`` + ``ppermute`` transposes the permutes, so the cooldown of
the reverse pipeline is derived automatically.

Embedding/positional/final-LN/head stay OUTSIDE the pipeline (replicated,
cheap); only the uniform block stack is staged — the shapes through every
stage are identical, which is what makes the single-program formulation
possible (and is why PP targets the transformer family, not the
heterogeneous conv stacks — those scale with DP/TP instead).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_pipeline_mesh(n_stages, devices=None):
    """1-axis ('stage',) mesh over the first n_stages devices."""
    import numpy
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    if n_stages > len(devices):
        raise ValueError("need %d devices, have %d"
                         % (n_stages, len(devices)))
    return Mesh(numpy.array(devices[:n_stages]), ("stage",))


def stack_blocks(blocks):
    """[per-block param dict] -> one pytree with a leading (L,) layer axis
    (the shardable form; L % n_stages must be 0)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def unstack_blocks(stacked, n_layers):
    """Inverse of stack_blocks (snapshot/restore round-trips)."""
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n_layers)]


def _stage_body(local_blocks, h, n_heads, block_size):
    """Apply this stage's L/S blocks sequentially (scan over the local
    slice of the layer axis)."""
    from veles_tpu.ops.transformer import block_forward

    def body(carry, blk):
        return block_forward(blk, carry, n_heads, block_size), None

    h, _ = jax.lax.scan(body, h, local_blocks)
    return h


def pipeline_blocks(stacked_blocks, h, mesh, n_heads, n_microbatches,
                    block_size=None):
    """Run the block stack over ``h`` (batch, seq, d) with the GPipe
    schedule on ``mesh``'s ``stage`` axis; returns the transformed
    activations, numerically identical to the sequential loop.

    batch must divide by n_microbatches; the layer axis of
    ``stacked_blocks`` must divide by the stage count.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from veles_tpu.compat import shard_map

    n_stages = mesh.shape["stage"]
    n_layers = jax.tree.leaves(stacked_blocks)[0].shape[0]
    if n_layers % n_stages:
        raise ValueError("n_layers %d %% n_stages %d != 0"
                         % (n_layers, n_stages))
    batch = h.shape[0]
    if batch % n_microbatches:
        raise ValueError("batch %d %% n_microbatches %d != 0"
                         % (batch, n_microbatches))
    x = h.reshape((n_microbatches, batch // n_microbatches) + h.shape[1:])

    def run(local_blocks, xloc):
        stage = jax.lax.axis_index("stage")
        n = jax.lax.psum(1, "stage")
        m = xloc.shape[0]
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t during warmup+steady ticks
            inject = jax.lax.dynamic_index_in_dim(
                xloc, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(stage == 0, inject, buf)
            h_out = _stage_body(local_blocks, h_in, n_heads, block_size)
            # the last stage finishes microbatch t-(S-1) at tick t.
            # Select only the SLOT, then update unconditionally — a where
            # around the whole buffer would defeat XLA's in-place
            # dynamic-update inside the loop (full copy per tick)
            out_t = t - (n - 1)
            write = jnp.logical_and(stage == n - 1,
                                    jnp.logical_and(out_t >= 0, out_t < m))
            slot_index = jnp.clip(out_t, 0, m - 1)
            slot = jax.lax.dynamic_index_in_dim(outs, slot_index, 0,
                                                keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(write, h_out, slot), slot_index, 0)
            # activation hop to the next stage (ICI neighbor copy)
            buf = jax.lax.ppermute(h_out, "stage", perm)
            return (buf, outs), None

        outs0 = jnp.zeros_like(xloc)
        buf0 = jnp.zeros_like(xloc[0])
        (_, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                    jnp.arange(m + n - 1))
        # replicate the last stage's results to every stage (out_specs P())
        return jax.lax.psum(
            jnp.where(stage == n - 1, outs, jnp.zeros_like(outs)), "stage")

    fn = shard_map(run, mesh=mesh, in_specs=(P("stage"), P()),
                   out_specs=P(), check_vma=False)
    want = NamedSharding(mesh, P("stage"))
    leaf = jax.tree.leaves(stacked_blocks)[0]
    already_placed = (
        not isinstance(leaf, jax.core.Tracer)   # tracers have no .sharding
        and isinstance(leaf, jax.Array)
        and leaf.sharding.is_equivalent_to(want, leaf.ndim))
    if not already_placed:
        # place once; callers in a training loop should pre-place (see
        # place_blocks) so repeated eager calls don't re-transfer params.
        # Under a trace this is the sharding constraint, not a copy.
        stacked_blocks = jax.device_put(stacked_blocks, want)
    out = fn(stacked_blocks, x)
    return out.reshape(h.shape)


def place_blocks(stacked_blocks, mesh):
    """Pre-place a stacked block pytree on the stage sharding (do this
    ONCE before a training loop; pipeline_blocks then skips the copy)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    return jax.device_put(stacked_blocks, NamedSharding(mesh, P("stage")))


def pipeline_lm_loss(params, tokens, mask, n_heads, mesh, n_microbatches,
                     block_size=None):
    """``transformer.lm_loss`` with the block stack executed by the GPipe
    pipeline; ``params["blocks"]`` is the STACKED pytree.  Equals the
    sequential loss (and its grads transpose through the pipeline) —
    the embed half and loss tail are the SAME shared helpers lm_loss
    composes, only the block-stack execution is swapped."""
    from veles_tpu.ops.transformer import embed_tokens, nll_from_hidden

    h = embed_tokens(params, tokens[:, :-1])
    h = pipeline_blocks(params["blocks"], h, mesh, n_heads,
                        n_microbatches, block_size)
    return nll_from_hidden(params, h, tokens[:, 1:], mask)
