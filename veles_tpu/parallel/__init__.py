"""Distribution — SPMD over a device mesh.

Replaces the reference's asynchronous master–slave parameter server
(ref: veles/server.py, veles/client.py, veles/distributable.py [H],
SURVEY §2.5) with the TPU-native equivalent BASELINE.json mandates: the
training step is jitted over a ``jax.sharding.Mesh``; gradient averaging is
the XLA all-reduce GSPMD inserts over ICI when the batch axis is sharded and
parameters are replicated.  Semantic change (documented, SURVEY §7): the
reference applied slave updates asynchronously; SPMD all-reduce is
synchronous — which converges at least as well, satisfying the val-acc
parity criterion.

Mesh axes:
- ``data`` — data parallelism (the reference's only strategy),
- ``model`` — optional tensor parallelism for wide layers (beyond-parity),
multi-host: ``jax.distributed.initialize`` + ``Loader.shard(process_index,
process_count)`` replaces master→slave minibatch index shipping.
"""

from __future__ import annotations

import numpy


def make_mesh(n_devices=None, model_parallel=1, devices=None):
    """Build a (data, model) mesh over the first ``n_devices`` devices."""
    import jax
    from jax.sharding import Mesh
    from veles_tpu.compat import ensure_partitionable_rng
    # sharded runs must draw the SAME dropout/augmentation bits as the
    # replicated runs they claim to reproduce (see compat)
    ensure_partitionable_rng()
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError("requested %d devices, have %d" % (n, len(devices)))
    if n % model_parallel:
        raise ValueError("n_devices %d not divisible by model_parallel %d"
                         % (n, model_parallel))
    grid = numpy.array(devices[:n]).reshape(n // model_parallel,
                                            model_parallel)
    return Mesh(grid, ("data", "model"))


def make_tp_mesh(tp, devices=None):
    """One-axis ``('tp',)`` mesh over ``tp`` devices for TENSOR-PARALLEL
    SERVING (``serving/lm_engine.py::LMEngine(tp=)``) — the serving
    sibling of :func:`make_mesh`'s ``model`` axis, kept separate because
    an engine mesh is a DEVICE SLICE: data-parallel engine replicas each
    build their own disjoint tp mesh out of one host's devices
    (``serving/router.py``), whereas the training mesh owns them all.
    ``devices`` defaults to the first ``tp`` of ``jax.devices()``."""
    import jax
    from jax.sharding import Mesh
    if tp < 2:
        raise ValueError("a tp mesh needs >= 2 devices (got tp=%d); "
                         "tp<2 serving runs without a mesh" % tp)
    devices = list(devices if devices is not None else jax.devices())
    if tp > len(devices):
        raise ValueError("requested tp=%d devices, have %d"
                         % (tp, len(devices)))
    return Mesh(numpy.array(devices[:tp]), ("tp",))


def model_shard_candidates(runner, min_width=1024):
    """Layer indices whose output width makes model-axis sharding pay
    (e.g. AlexNet's 4096-wide FC trunk).  Narrow layers stay replicated —
    a sharded 10-wide softmax costs more in collectives than it saves."""
    return [i for i, entry in enumerate(runner.state)
            if entry and entry["w"].shape[-1] >= min_width]


class ShardedTrainer:
    """Runs a FusedRunner's steps SPMD over a mesh.

    Parameters live replicated (or model-axis sharded for listed layers);
    the batch is sharded over ``data``.  Gradients contract over the sharded
    batch axis, so GSPMD inserts the ICI all-reduce automatically — that
    all-reduce IS the reference's master-side gradient averaging, minus the
    ZeroMQ hop.
    """

    def __init__(self, runner, mesh, model_shard_layers=()):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.runner = runner
        self.mesh = mesh
        #: True when the mesh spans multiple processes (multi-host SPMD):
        #: arrays are then assembled from per-process local shards instead
        #: of device_put (which requires every device to be addressable)
        self.multiprocess = len({d.process_index
                                 for d in mesh.devices.flat}) > 1
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P("data"))
        #: epoch-scan placement: (B, mb) plan matrices sharded over the
        #: data axis along the minibatch dimension; dataset replicated
        self._mb_shard = NamedSharding(mesh, P(None, "data"))
        self._data = None
        self._labels = None
        shardings = []
        for i, entry in enumerate(runner.state):
            if not entry:      # weightless layer (pooling, dropout, crop…)
                shardings.append({})
                continue
            if i in model_shard_layers:
                # output-dimension (column/channel) sharding: dense weights
                # are (n_in, n_out), conv weights HWIO (kh, kw, cin, cout) —
                # the last axis is the output width either way, the split
                # the reference could not express at all (SURVEY §2.5
                # "beyond-parity" TP row)
                ndim = entry["w"].ndim
                w = NamedSharding(mesh, P(*([None] * (ndim - 1) + ["model"])))
                b = NamedSharding(mesh, P("model"))
            else:
                w = b = self._repl
            # optimizer state shards with the array it accompanies: keys
            # ending in "w" are weight-shaped (w, vw, aw), keys ending in
            # "b" bias-shaped (b, vb, ab) — GradientDescentBase.state_entry
            # guarantees the convention
            spec = {k: (w if k.endswith("w") else b) for k in entry}
            shardings.append(spec)
        self.state_shardings = shardings
        #: global train-step counter (lr policies); see train_step
        self.step_count = 0
        # Multi-process placement cuts every device's shard from the
        # process-LOCAL host copy (_put), which is only correct when all
        # processes built bit-identical initial state — divergent init
        # (version skew, nondeterministic op order) would silently
        # assemble a Frankenstein tensor on a cross-process model axis.
        # Cross-check a digest first, mirroring the place_dataset guard.
        if self.multiprocess:
            import zlib
            from jax.experimental import multihost_utils
            digest = [zlib.crc32(numpy.ascontiguousarray(leaf).tobytes())
                      for leaf in jax.tree.leaves(runner.state)]
            multihost_utils.assert_equal(
                numpy.asarray(digest, numpy.uint32),
                "initial runner state differs across processes — every "
                "process must build bit-identical params (same seed, "
                "pinned PRNG streams) before ShardedTrainer assembles "
                "shards from local copies")
        #: device state, placed according to the sharding plan (replicated
        #: state: every process holds the full value, so local data == the
        #: global array in the multi-process assembly)
        self.state = jax.tree.map(self._put, runner.state, shardings)
        # out_shardings pins the updated state to the plan — otherwise
        # GSPMD may re-shard it to whatever propagation preferred
        # _step_fn: the runner's configured per-minibatch step
        # (monolithic or gradient-accumulating) — grad_accum must hold
        # on the SPMD path exactly as it does single-chip
        self._train = jax.jit(runner._step_fn, donate_argnums=(0,),
                              out_shardings=(shardings, None))
        self._eval = jax.jit(runner._eval_step)

    def _put(self, arr, sharding):
        """Place PARAMETER/OPTIMIZER state.  Multi-process: every process
        builds the identical full host value (same seed, pinned streams),
        so each device's shard is cut from the local full copy by global
        index — which supports ANY sharding, including a model axis that
        spans processes (megatron-style TP across hosts rides the same
        path as within-host TP)."""
        import jax
        if arr is None:
            return None
        if self.multiprocess:
            host = numpy.asarray(arr)
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
        return jax.device_put(arr, sharding)

    def put_batch(self, x, labels, mask):
        """Shard one (padded, static-shape) minibatch over the data axis.

        Single-process: the arrays are GLOBAL and device_put splits them.
        Multi-process: each process passes its LOCAL rows — the slice of
        the global batch its data-coordinates cover, exactly what
        ``Loader.shard_spmd`` yields when driven by
        :func:`spmd_loader_shard` (processes that share data-coordinates,
        i.e. a cross-process model axis, pass identical rows) — and the
        global array is assembled with
        ``jax.make_array_from_process_local_data``.
        """
        import jax
        if self.multiprocess:
            put = (lambda a: jax.make_array_from_process_local_data(
                self._batch, numpy.asarray(a)))
        else:
            put = lambda a: jax.device_put(a, self._batch)
        return put(x), put(labels), put(mask)

    def train_step(self, x, labels, mask, batch_size, rng=None, step=None):
        """One SPMD train step; ``step`` defaults to an internal counter so
        lr policies decay in the distributed path exactly as they do under
        FusedStep (pass it explicitly to resume from a checkpointed step)."""
        import jax.numpy as jnp
        if rng is None and self.runner._has_stochastic:
            from veles_tpu import prng
            rng = prng.get("dropout").key()
        if step is None:
            step = self.step_count
        x, labels, mask = self.put_batch(x, labels, mask)
        self.state, metrics = self._train(
            self.state, x, labels, mask, jnp.asarray(batch_size, jnp.int32),
            rng, jnp.asarray(step, jnp.int32))
        self.step_count = int(step) + 1
        return metrics

    def train_step_pending(self, x, labels, mask, batch_size, rng=None,
                           step=0):
        """Graph-mode (FusedStep) variant of :meth:`train_step`: computes
        the updated state WITHOUT committing it, so FusedCommit can adopt
        or discard it after Decision gates — the same pending/commit
        dance the single-device path does.  Non-donating (the current
        state must survive a discarded update)."""
        import jax
        import jax.numpy as jnp
        if not hasattr(self, "_train_pending"):
            self._train_pending = jax.jit(
                self.runner._step_fn,
                out_shardings=(self.state_shardings, None))
        x, labels, mask = self.put_batch(x, labels, mask)
        return self._train_pending(
            self.state, x, labels, mask,
            jnp.asarray(batch_size, jnp.int32), rng,
            jnp.asarray(step, jnp.int32))

    def eval_step(self, x, labels, mask):
        x, labels, mask = self.put_batch(x, labels, mask)
        return self._eval(self.state, x, labels, mask)

    def reload_from_runner(self):
        """Re-place device state from the runner's host-side state —
        the restore-side inverse of :meth:`sync_to_runner` (snapshot
        restore rewrites the unit Vectors and refreshes runner.state;
        this pushes it back out over the mesh, digest-guarded in
        multi-process mode like __init__)."""
        import jax
        if self.multiprocess:
            import zlib
            from jax.experimental import multihost_utils
            digest = [zlib.crc32(numpy.ascontiguousarray(
                numpy.asarray(leaf)).tobytes())
                for leaf in jax.tree.leaves(self.runner.state)]
            multihost_utils.assert_equal(
                numpy.asarray(digest, numpy.uint32),
                "restored runner state differs across processes — every "
                "process must restore the same snapshot")
        self.state = jax.tree.map(self._put, self.runner.state,
                                  self.state_shardings)

    # ------------------------------------------------- epoch-scan (SPMD)
    # GLOBAL-plan API: every process passes the SAME full dataset and the
    # SAME (B, mb) epoch plan — unlike the per-minibatch path, which
    # consumes each process's shard_spmd-local rows.  Multi-process
    # callers therefore plan from an UNsharded loader (the plan is
    # deterministic from the shared PRNG seed); train_epoch cross-checks
    # the plan across processes to fail loudly instead of silently
    # training on mismatched batches.
    def place_dataset(self, data, labels=None):
        """Put the full GLOBAL dataset in HBM, replicated over the mesh,
        for the one-dispatch-per-epoch path (labels None for AE
        targets).  Every process must pass identical arrays —
        cross-checked by digest, so a process feeding different data
        fails here instead of silently diverging."""
        if self.multiprocess:
            import zlib
            from jax.experimental import multihost_utils
            digest = [zlib.crc32(numpy.ascontiguousarray(data).tobytes())]
            if labels is not None:
                digest.append(zlib.crc32(
                    numpy.ascontiguousarray(labels).tobytes()))
            multihost_utils.assert_equal(
                numpy.asarray(digest, numpy.uint32),
                "place_dataset arrays differ across processes — the "
                "epoch-scan path needs the identical GLOBAL dataset "
                "everywhere")
        self._data = self._put(data, self._repl)
        self._labels = (self._put(labels, self._repl)
                        if labels is not None else None)

    def _place_plan(self, idx, mask, rng=None):
        """Shared guard + placement for train_epoch/eval_epoch: validates
        the plan, cross-checks it (and the rng key, whose divergence
        would silently desynchronize dropout masks across hosts) in
        multi-process mode, and uploads the plan matrices data-sharded."""
        if self._data is None:
            raise ValueError("call place_dataset(data, labels) first")
        if idx.shape[-1] % self.mesh.shape["data"]:
            raise ValueError(
                "minibatch size %d not divisible by data-axis size %d"
                % (idx.shape[-1], self.mesh.shape["data"]))
        if self.multiprocess:
            from jax.experimental import multihost_utils
            tree = (numpy.asarray(idx), numpy.asarray(mask))
            if rng is not None:
                tree += (numpy.asarray(rng),)
            multihost_utils.assert_equal(
                tree,
                "epoch-scan plan/rng differs across processes — build "
                "the plan from an UNsharded loader (global plan, not "
                "shard_spmd) and derive the rng from the shared seed")
        self._ensure_epoch_jits()
        # plan matrices shard over the data axis along the (last)
        # minibatch dimension — (B, mb) per-epoch, (k, B, mb) chunked
        from jax.sharding import NamedSharding, PartitionSpec as P
        shard = (self._mb_shard if idx.ndim == 2 else
                 NamedSharding(self.mesh, P(None, None, "data")))
        return (self._put(numpy.asarray(idx, numpy.int32), shard),
                self._put(numpy.asarray(mask, numpy.float32), shard))

    def train_epoch(self, idx, mask, rng=None, step0=None):
        """One device dispatch per EPOCH, data-parallel inside the scan.

        The single-chip fast path (FusedRunner._epoch_train: lax.scan over
        the minibatch index matrix, SURVEY §3.1 rebuild) runs unchanged
        under the mesh — the ONLY distribution work is placement: the
        dataset is replicated, and ``idx``/``mask`` (B, mb) are sharded
        over the data axis along the minibatch dimension, so each scan
        step's gather yields a batch-sharded ``x`` and GSPMD propagates
        DP (and any model-axis sharding of the params) through the whole
        epoch, inserting one gradient all-reduce per step.  Zero host
        work between minibatches, N-chip parallel.
        """
        import jax.numpy as jnp
        self.runner.require_epoch_rng(rng)
        idx_g, mask_g = self._place_plan(idx, mask, rng)
        if step0 is None:
            step0 = self.step_count
        self.state, totals = self._epoch_train_jit(
            self.state, self._data, self._labels, idx_g, mask_g, rng,
            jnp.asarray(step0, jnp.int32))
        self.step_count = int(step0) + idx.shape[0]
        return totals

    def train_epochs(self, idx, mask, rng=None, step0=None):
        """``k`` epochs in ONE dispatch under the mesh
        (FusedRunner._epoch_chunk): ``idx``/``mask`` are (k, B, mb) —
        one independently shuffled plan per epoch, precomputed on the
        host — and the per-epoch metric totals come back stacked
        (k rows), so the host still sees every epoch's metrics, at
        k-epoch readback granularity instead of k execute round-trips.
        Through a tunnel an execute RPC costs ~0.1-1 s; this divides
        that cost by k.  Trade-off: early-stopping decisions lag up to
        k-1 epochs."""
        import functools
        import jax
        import jax.numpy as jnp
        idx = numpy.asarray(idx)
        if idx.ndim != 3:
            raise ValueError("train_epochs wants (k, B, mb) per-epoch "
                             "plans; use train_epoch for a single epoch")
        k = idx.shape[0]
        self.runner.require_epoch_rng(rng)
        idx_g, mask_g = self._place_plan(idx, mask, rng)
        cache = getattr(self, "_chunk_jits", None)
        if cache is None:
            cache = self._chunk_jits = {}
        if k not in cache:
            cache[k] = jax.jit(
                functools.partial(self.runner._epoch_chunk, k),
                donate_argnums=(0,),
                out_shardings=(self.state_shardings, None))
        if step0 is None:
            step0 = self.step_count
        self.state, stacked = cache[k](
            self.state, self._data, self._labels, idx_g, mask_g, rng,
            jnp.asarray(step0, jnp.int32))
        self.step_count = int(step0) + k * idx.shape[-2]
        return stacked

    def train_epochs_eval(self, idx, mask, vidx, vmask, rng=None,
                          step0=None, eval_first=False):
        """``k`` (train epoch + validation eval) rounds in ONE dispatch
        under the mesh (FusedRunner._epoch_chunk_eval) — the convergence
        loop's body at 1 execute per k epochs, SPMD.  idx/mask are
        (k, B, mb) per-epoch plans; vidx/vmask the fixed validation
        plan.  Returns (train totals stacked, val totals stacked)."""
        import functools
        import jax
        import jax.numpy as jnp
        idx = numpy.asarray(idx)
        if idx.ndim != 3:
            raise ValueError("train_epochs_eval wants (k, B, mb) "
                             "per-epoch plans")
        k = idx.shape[0]
        self.runner.require_epoch_rng(rng)
        idx_g, mask_g = self._place_plan(idx, mask, rng)
        vidx_g, vmask_g = self._place_plan(vidx, vmask)
        cache = getattr(self, "_chunk_eval_jits", None)
        if cache is None:
            cache = self._chunk_eval_jits = {}
        if (k, eval_first) not in cache:
            cache[(k, eval_first)] = jax.jit(
                functools.partial(self.runner._epoch_chunk_eval, k,
                                  eval_first=eval_first),
                donate_argnums=(0,),
                out_shardings=(self.state_shardings, None, None, None))
        if step0 is None:
            step0 = self.step_count
        self.state, train_stack, val_stack, _ = cache[(k, eval_first)](
            self.state, self._data, self._labels, idx_g, mask_g, vidx_g,
            vmask_g, rng, jnp.asarray(step0, jnp.int32))
        self.step_count = int(step0) + k * idx.shape[-2]
        return train_stack, val_stack

    def chunk_eval_pending(self, idx, mask, vidx, vmask, rng=None,
                           step0=None, eval_first=False, tidx=None,
                           tmask=None):
        """Driver-facing variant of :meth:`train_epochs_eval`: k epochs
        with per-epoch (k, B, mb) plans plus per-epoch valid (and
        optional test) evals in one dispatch — NON-donating and
        NON-committing.  ``self.state`` stays at the chunk input so the
        epoch-scan driver can replay a mid-chunk completion exactly
        (see epoch_driver.py); commit with ``self.state = new_state``.
        Returns (new_state, train stacked, val stacked, test stacked or
        None)."""
        import functools
        import jax
        import jax.numpy as jnp
        idx = numpy.asarray(idx)
        if idx.ndim != 3:
            raise ValueError("chunk_eval_pending wants (k, B, mb) "
                             "per-epoch plans")
        k = idx.shape[0]
        self.runner.require_epoch_rng(rng)
        idx_g, mask_g = self._place_plan(idx, mask, rng)
        vidx_g, vmask_g = self._place_plan(vidx, vmask)
        tidx_g = tmask_g = None
        if tidx is not None:
            tidx_g, tmask_g = self._place_plan(tidx, tmask)
        cache = getattr(self, "_chunk_pending_jits", None)
        if cache is None:
            cache = self._chunk_pending_jits = {}
        if (k, eval_first) not in cache:
            cache[(k, eval_first)] = jax.jit(
                functools.partial(self.runner._epoch_chunk_eval, k,
                                  eval_first=eval_first),
                out_shardings=(self.state_shardings, None, None, None))
        if step0 is None:
            step0 = self.step_count
        return cache[(k, eval_first)](
            self.state, self._data, self._labels, idx_g, mask_g, vidx_g,
            vmask_g, rng, jnp.asarray(step0, jnp.int32), tidx=tidx_g,
            tmask=tmask_g)

    def _ensure_epoch_jits(self):
        import jax
        if not hasattr(self, "_epoch_train_jit"):
            self._epoch_train_jit = jax.jit(
                self.runner._epoch_train, donate_argnums=(0,),
                out_shardings=(self.state_shardings, None))
            self._epoch_eval_jit = jax.jit(self.runner._epoch_eval)

    def eval_epoch(self, idx, mask):
        """Whole-set evaluation in one dispatch (see train_epoch)."""
        idx_g, mask_g = self._place_plan(idx, mask)
        return self._epoch_eval_jit(self.state, self._data, self._labels,
                                    idx_g, mask_g)

    @staticmethod
    def fetch(tree):
        """Host values of replicated outputs (metrics), multi-process safe:
        reads the local replica instead of requiring full addressability."""
        import jax

        def leaf(a):
            if isinstance(a, jax.Array) and not a.is_fully_addressable:
                return numpy.asarray(a.addressable_data(0))
            return numpy.asarray(a)
        return jax.tree.map(leaf, tree)

    def sync_to_runner(self):
        """Gather sharded state back into the runner (for snapshots)."""
        import jax
        self.runner.state = jax.tree.map(jax.numpy.asarray,
                                         self.fetch(self.state))
        self.runner.sync_to_units()


def spmd_loader_shard(mesh):
    """(shard_index, shard_count) for ``Loader.shard_spmd`` derived from
    the mesh layout, generalizing "shard by process" to meshes whose
    ``model`` axis spans processes.

    The batch is sharded over the ``data`` axis only, so the rows a
    process must load are determined by which data-coordinates its
    devices cover: processes covering the same block of data-coordinates
    (they sit on different ``model`` columns of the same rows) must load
    IDENTICAL rows — the input replication a cross-host tensor-parallel
    layout requires.  Falls back to the familiar (process_index,
    process_count) on the standard blocked layout, where each process
    owns its own data block.
    """
    import jax
    if "data" not in mesh.axis_names:
        raise ValueError("mesh has no 'data' axis (axes: %r)"
                         % (mesh.axis_names,))
    # blocks are computed over the DATA axis wherever it sits in the
    # grid (put_batch shards by axis name, so position must not matter)
    grid = numpy.moveaxis(mesh.devices,
                          mesh.axis_names.index("data"), 0)
    grid = grid.reshape(grid.shape[0], -1)
    rows_of = {}
    for p in {d.process_index for d in grid.flat}:
        rows = tuple(sorted({r for r in range(grid.shape[0])
                             if any(d.process_index == p
                                    for d in grid[r].flat)}))
        rows_of[p] = rows
    blocks = sorted(set(rows_of.values()), key=lambda t: t[0])
    flat = [r for b in blocks for r in b]
    if flat != list(range(grid.shape[0])) or \
            len({len(b) for b in blocks}) != 1:
        raise ValueError(
            "mesh data-axis layout is not a contiguous equal partition "
            "across processes (blocks: %r) — deterministic loader "
            "sharding needs one; reorder the device grid" % (blocks,))
    return blocks.index(rows_of[jax.process_index()]), len(blocks)


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None):
    """Multi-host entry: jax.distributed + per-host loader sharding.

    The reference's launcher started a master and N slave processes
    (SURVEY §3.2); the TPU equivalent is one process per host joining the
    same computation (DCN for control, ICI/DCN collectives for data).
    """
    import jax
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()
