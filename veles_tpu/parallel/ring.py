"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context scaling beyond one chip: the sequence axis is sharded over the
``seq`` mesh axis; each device holds its query block permanently while
key/value blocks ROTATE around the ring via ``lax.ppermute`` over ICI, with
the same online-softmax accumulation as the single-chip blockwise kernel
(veles_tpu.ops.attention._online_update), so memory per chip is
O(seq/n_devices) and the KV transfer overlaps compute around the ring.

This is the idiomatic TPU mechanism SURVEY §5.7 names for the roadmap
(shard_map over a context axis + ppermute); the reference has no attention
at all, so this module is pure beyond-parity capability.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from veles_tpu.ops.attention import (_online_update,
                                     band_bias, NEG_INF)


def make_seq_mesh(n_devices=None, data_parallel=1, devices=None):
    """(data, seq) mesh: batch over 'data', sequence ring over 'seq'."""
    import numpy
    from jax.sharding import Mesh
    from veles_tpu.compat import ensure_partitionable_rng
    ensure_partitionable_rng()
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    if n % data_parallel:
        raise ValueError("n_devices %d not divisible by data_parallel %d"
                         % (n, data_parallel))
    grid = numpy.array(devices[:n]).reshape(data_parallel,
                                            n // data_parallel)
    return Mesh(grid, ("data", "seq"))


def _ring_attention_local(q, k, v, axis_name, causal, window=None,
                          sinks=0):
    """Per-shard body (runs under shard_map): q/k/v are the LOCAL sequence
    blocks (batch, heads, s_local, dh)."""
    n = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    q_pos = my_index * s_local + jnp.arange(s_local)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o_l_m, kv = carry
        k_blk, v_blk = kv
        # kv block currently held originated on device (my_index - step) % n
        src = (my_index - step) % n
        def attend(c):
            # the shared global-position band (attention.band_bias):
            # the window just masks across shard borders.  Step 0 is
            # the own block (every query sees itself), so the online
            # max is finite before any fully-masked distant block
            # arrives — same transient-safety argument as
            # blockwise_attention.  Built INSIDE the branch so skipped
            # steps skip the (s_local x s_local) mask too.
            bias = (band_bias(q_pos,
                              src * s_local + jnp.arange(s_local),
                              causal, window, q.dtype, sinks=sinks)
                    if causal else None)
            return _online_update(c, q, k_blk, v_blk, bias)

        if causal:
            # EARLY EXIT: skip the attention math entirely for blocks
            # with no live (query, key) pair — future blocks under
            # causality, too-old blocks under a window; most ring steps
            # are then just the ppermute.  A pair (q, k) is live iff
            # k <= q and (no window or q - k < W); over the block's key
            # span [k_first, k_last] and this device's query span
            # [q_first, q_last] that reduces to the interval test
            #   k_first <= q_last  AND  k_last > q_first - W.
            k_first = src * s_local
            k_last = k_first + s_local - 1
            live = k_first <= q_pos[-1]
            if window:
                in_band = k_last > q_pos[0] - window
                if sinks:
                    in_band |= k_first < sinks
                live &= in_band
            o_l_m = jax.lax.cond(live, attend, lambda c: c, o_l_m)
        else:
            o_l_m = attend(o_l_m)
        # rotate kv around the ring for the next step (ICI neighbor copy)
        kv = jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis_name, perm), kv)
        return (o_l_m, kv), None

    # derive the accumulators from q so they inherit its device-varying
    # axes — fresh constants would make the scan carry types mismatch
    o0 = jnp.zeros_like(q)
    l0 = q[..., 0] * 0.0
    m0 = q[..., 0] * 0.0 + NEG_INF
    (o_l_m, _), _ = jax.lax.scan(body, ((o0, l0, m0), (k, v)),
                                 jnp.arange(n))
    o, l, _ = o_l_m
    return o / l[..., None]


def ring_attention(q, k, v, mesh, causal=True, seq_axis="seq",
                   data_axis="data", window=None, sinks=0):
    """Sequence-parallel attention over ``mesh``.

    q, k, v: (batch, heads, seq, head_dim) GLOBAL arrays; the sequence axis
    is sharded over ``seq_axis``, batch over ``data_axis``; output sharding
    matches q.  Numerically equals dense ``attention(q, k, v, causal)``;
    ``window=W`` composes (equals the dense sliding-window form — global
    positions, so the band crosses shard borders correctly).  Ring steps
    whose whole block is outside the band skip the attention math (only
    the ppermute runs), so per-device compute under a small window is
    O(s_local + W) keys per query block rather than O(seq).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    from veles_tpu.compat import shard_map

    if window and not causal:
        raise ValueError("window requires causal=True")
    spec = P(data_axis, None, seq_axis, None)
    # check_vma=False: jax 0.4.x's replication checker cannot unify the
    # two branches of the early-exit lax.cond under grad ("mismatched
    # replication types"); the check is a static analysis only — every
    # array here is device-varying along the ring anyway, so disabling
    # it changes nothing numerically (forward+grad parity pinned in
    # tests/test_attention.py)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=seq_axis,
                          causal=causal, window=window, sinks=sinks),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    q = jax.device_put(q, NamedSharding(mesh, spec))
    k = jax.device_put(k, NamedSharding(mesh, spec))
    v = jax.device_put(v, NamedSharding(mesh, spec))
    return fn(q, k, v)
