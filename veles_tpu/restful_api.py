"""REST serving — HTTP JSON in, forward pass out.

Ref: veles/restful_api.py::RESTfulAPI [M] (SURVEY §2.1, §3.4): feed JSON
input through a trained forward pass over HTTP.  stdlib http.server on a
background thread (the reference used Twisted web); the forward is the
fused chain jitted once, so per-request work is one device dispatch.

Usage::

    api = RESTfulAPI(workflow)          # a trained StandardWorkflow
    api.start(port=0)                   # 0 → ephemeral
    ... POST {"input": [[...]]} to http://host:port/predict ...
    api.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.logger import Logger


class RESTfulAPI(Logger):
    def __init__(self, workflow, normalizer=None, forward=None,
                 handler=None):
        self.workflow = workflow
        #: optional input normalizer (a loader's fitted normalizer) applied
        #: before the forward, so clients send raw feature scale
        self.normalizer = normalizer
        self._server = None
        self._thread = None
        #: explicit forward callable (batch ndarray -> ndarray) — used by
        #: artifact serving, where there is no workflow at all
        self._forward = forward
        #: full-request handler (payload dict -> response dict); when set
        #: it replaces the predict flow entirely — used by serve_lm, whose
        #: requests carry decoding knobs beyond "input"
        self._handler = handler

    # ------------------------------------------------------------- inference
    def _ensure_forward(self):
        if self._forward is not None:
            return self._forward
        runner = getattr(self.workflow, "_fused_runner", None)
        if runner is not None:
            fn = runner.eval_forward()

            def forward(x):
                return numpy.asarray(fn(runner.state, x))
        else:
            units = self.workflow.forwards

            def forward(x):
                import jax.numpy as jnp
                h = jnp.asarray(x)
                for unit in units:
                    entry = {}
                    if unit.has_params:
                        entry = {"w": unit.weights.devmem}
                        if unit.include_bias:
                            entry["b"] = unit.bias.devmem
                    h = unit.apply_fused(h, entry, None, False)
                return numpy.asarray(h)
        self._forward = forward
        return forward

    def predict(self, batch):
        x = numpy.asarray(batch, numpy.float32)
        if self.normalizer is not None:
            x = self.normalizer.apply(x)
        probs = self._ensure_forward()(x)
        return {"output": probs.tolist(),
                "argmax": probs.reshape(len(probs), -1)
                               .argmax(axis=1).tolist()}

    # ---------------------------------------------------------------- server
    def start(self, host="127.0.0.1", port=8180):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path.rstrip("/") != "/predict":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    result = (api._handler(payload)
                              if api._handler is not None
                              else api.predict(payload["input"]))
                    body = json.dumps(result).encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:   # noqa: BLE001 — reported to client
                    body = json.dumps({"error": str(e)}).encode("utf-8")
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            def log_message(self, fmt, *args):
                api.debug("restful: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.port = self._server.server_address[1]
        self.info("REST serving on http://%s:%d/predict", host, self.port)
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


def serve_lm(workflow, host="127.0.0.1", port=8180, max_new=256):
    """Serve a trained transformer-trainer workflow (e.g. char_lm) for
    autoregressive continuation: POST ``{"input": [[tok, ...]],
    "n_new": N, "temperature": T, "top_k": K, "seed": S}`` to
    ``/predict`` returns ``{"tokens": [[...]]}`` — prompt plus
    continuation per row.  Decoding is the KV-cached
    ``transformer.generate`` path, one jitted dispatch per request.
    Compile count and per-request cost are both BOUNDED against
    adversarial or merely varied clients:

    - prompt lengths are BUCKETED — the prompt is right-padded to the
      next power of two and decoded with a traced ``true_len`` (bit-exact
      under causal attention, see ``transformer._generate_impl``), so
      compiles grow with log2(max_len), not with every distinct prompt
      length;
    - ``n_new`` is quantized into a few static TIERS (clamped to
      ``max_new``), so an n_new=1 request pays a short tier's decode,
      not the full ``max_new``, while per-value recompiles stay
      impossible.  top_k remains jit-static but vocab-bounded.
    """
    from veles_tpu.ops.transformer import trainer_sample_tokens
    trainer = workflow.trainer
    # marshalled ONCE (params are frozen while serving; pipelined
    # trainers pay the block unstack here, not per request)
    params = trainer._to_portable(trainer.params)
    cache_len = int(trainer.max_len)
    # geometric ladder bounds BOTH the compile count (one generate
    # program per tier) and the decode overshoot (≤4× the requested
    # n_new; {8,32,max} alone made an n_new=40 request pay a full
    # max_new=256 decode)
    tiers = sorted({t for t in (8, 32, 128, max_new) if t <= max_new})

    def handler(request):
        prompt = numpy.asarray(request["input"], numpy.int32)
        want = min(int(request.get("n_new", 32)), max_new)
        if want < 1:        # n_new=0: echo/validation probe, no decode
            return {"tokens": prompt.tolist()}
        s_true = prompt.shape[1]
        headroom = cache_len - s_true
        if headroom < 1:
            raise ValueError("prompt length %d leaves no room to decode "
                             "(max_len %d)" % (s_true, cache_len))
        # decode length: round the request UP to a tier; near the cache
        # cap fall back to the largest tier that fits (or the exact
        # headroom when even the smallest doesn't — rare, self-limiting)
        run = next((t for t in tiers if t >= want), tiers[-1])
        if run > headroom:
            fitting = [t for t in tiers if t <= headroom]
            run = fitting[-1] if fitting else headroom
        # prompt bucket: right-pad to the next power of two that still
        # fits the cache; true_len keeps decoding bit-exact
        bucket = 16
        while bucket < s_true:
            bucket *= 2
        bucket = min(bucket, cache_len - run)
        if bucket > s_true:
            prompt = numpy.pad(prompt, ((0, 0), (0, bucket - s_true)))
        top_k = request.get("top_k")
        out = trainer_sample_tokens(
            trainer, prompt, n_new=run,
            temperature=float(request.get("temperature", 0.0)),
            seed=int(request.get("seed", 0)), params=params,
            max_len=cache_len,
            top_k=int(top_k) if top_k is not None else None,
            true_len=s_true)
        # the continuation lands after the PADDED width; reply with the
        # true prompt plus min(want, run) new tokens
        new = out[:, prompt.shape[1]:prompt.shape[1] + min(want, run)]
        return {"tokens": numpy.concatenate(
            [out[:, :s_true], new], axis=1).tolist()}

    return RESTfulAPI(None, handler=handler).start(host=host, port=port)


def serve_artifact(path, host="127.0.0.1", port=8180):
    """Serve a StableHLO export artifact (veles_tpu.export) WITHOUT
    constructing any training workflow — the libVeles serving path
    (SURVEY §2.4/§3.4): load weights + compiled forward, start HTTP."""
    from veles_tpu.export import load_model
    model = load_model(path)
    return RESTfulAPI(None, forward=model.predict).start(host=host,
                                                         port=port)


def serve_snapshot(path, host="127.0.0.1", port=8180, build=None):
    """CLI helper: restore a snapshot into a rebuilt workflow and serve it.

    ``build`` is a zero-arg callable returning the (initialized) workflow —
    usually a sample's ``build`` + ``initialize``; the snapshot then restores
    the trained weights (SURVEY §3.3/§3.4 snapshot-is-the-artifact flow).
    """
    from veles_tpu import snapshotter
    wf = build()
    snapshotter.restore(wf, path)
    return RESTfulAPI(wf).start(host=host, port=port)
