"""REST serving — HTTP JSON in, forward pass out.

Ref: veles/restful_api.py::RESTfulAPI [M] (SURVEY §2.1, §3.4): feed JSON
input through a trained forward pass over HTTP.  stdlib http.server on a
background thread (the reference used Twisted web); the forward is the
fused chain jitted once, so per-request work is one device dispatch.

Two serving modes (ISSUE 1):

- DIRECT (default) — each request runs its own dispatch; right for
  single-user/debug serving.
- BATCHED — :meth:`RESTfulAPI.enable_batching` routes ``/predict``
  through :class:`veles_tpu.serving.MicroBatcher`: concurrent requests
  coalesce into one padded power-of-two-bucket dispatch, a full queue
  answers HTTP 429 with ``Retry-After``, and requests queued past their
  deadline are shed with 503.  ``serve_lm(slots=N)`` likewise routes
  greedy decode through :class:`veles_tpu.serving.LMEngine` (continuous
  batching over a shared KV cache); sampled requests keep the direct
  path.

Error contract: every non-200 reply is structured JSON
(``{"error": ...}``) with a meaningful status — 400 malformed request,
404 unknown path, 413 oversized body (``max_body``), 429 overload
(+``Retry-After`` seconds), 500 server fault, 503 shed past deadline.
``GET /metrics.json`` (snapshot) and ``GET /metrics`` (Prometheus text)
expose the serving counters on the serving port itself.

Usage::

    api = RESTfulAPI(workflow)          # a trained StandardWorkflow
    api.start(port=0)                   # 0 → ephemeral
    ... POST {"input": [[...]]} to http://host:port/predict ...
    api.stop()
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy

from veles_tpu.logger import Logger


class RESTfulAPI(Logger):
    def __init__(self, workflow, normalizer=None, forward=None,
                 handler=None, metrics=None, max_body=16 << 20,
                 faults=None, tracer=None, telemetry=None, slo=None):
        self.workflow = workflow
        #: optional TimeSeriesStore (ISSUE 14): continuous telemetry
        #: over the serving metrics — ``GET /timeseries.json?window=S``
        #: (owned by serve_lm; stopped with the server)
        self.telemetry = telemetry
        #: optional SLOMonitor (ISSUE 14): burn-rate objectives over
        #: the store — ``GET /slo.json``
        self.slo = slo
        #: optional serving FaultPlan (ISSUE 10): the ``http.request``
        #: site fires per POST — transient InjectedHTTPError replies
        #: (the retryable-infrastructure-blip shape) and latency
        #: spikes; a no-op when None
        self.faults = faults
        #: optional serving SpanTracer (ISSUE 12): every POST opens an
        #: ``http.request`` root span keyed by the request id, and
        #: ``GET /trace.json?last=N`` exports the flight recorder as
        #: Chrome-trace JSON; a no-op when None
        self.tracer = tracer
        #: optional HealthChecker owned by serve_lm (stopped with the
        #: server)
        self.health_checker = None
        #: optional ModelManager publisher loop owned by serve_lm
        #: (stopped with the server, before the engines)
        self.model_manager = None
        #: optional input normalizer (a loader's fitted normalizer) applied
        #: before the forward, so clients send raw feature scale
        self.normalizer = normalizer
        self._server = None
        self._thread = None
        #: explicit forward callable (batch ndarray -> ndarray) — used by
        #: artifact serving, where there is no workflow at all
        self._forward = forward
        #: full-request handler (payload dict -> response dict); when set
        #: it replaces the predict flow entirely — used by serve_lm, whose
        #: requests carry decoding knobs beyond "input"
        self._handler = handler
        #: serving counters (ServingMetrics) — end-to-end latency and
        #: response counts are recorded HERE (engines own queue/dispatch
        #: facts), so sharing one instance with an engine double-counts
        #: nothing
        self.metrics = metrics
        #: request bodies beyond this are refused with 413 before parsing
        self.max_body = int(max_body)
        #: optional MicroBatcher the predict path routes through
        self.batcher = None
        #: optional LMEngine owned by serve_lm (stopped with the server)
        self.lm_engine = None

    # ------------------------------------------------------------- inference
    def _ensure_forward(self):
        if self._forward is not None:
            return self._forward
        runner = getattr(self.workflow, "_fused_runner", None)
        if runner is not None:
            fn = runner.eval_forward()

            def forward(x):
                return numpy.asarray(fn(runner.state, x))
        else:
            units = self.workflow.forwards

            def forward(x):
                import jax.numpy as jnp
                h = jnp.asarray(x)
                for unit in units:
                    entry = {}
                    if unit.has_params:
                        entry = {"w": unit.weights.devmem}
                        if unit.include_bias:
                            entry["b"] = unit.bias.devmem
                    h = unit.apply_fused(h, entry, None, False)
                return numpy.asarray(h)
        self._forward = forward
        return forward

    def _infer_sample_shape(self):
        """Best-effort input sample shape (for bucket warmup): the
        loader's minibatch row shape when a workflow is attached."""
        data = getattr(getattr(self.workflow, "loader", None),
                       "minibatch_data", None)
        shape = getattr(data, "shape", None)
        return tuple(shape[1:]) if shape and len(shape) > 1 else None

    def enable_batching(self, max_batch=64, queue_depth=128,
                        batch_wait_s=0.002, deadline_s=2.0,
                        sample_shape=None, metrics=None,
                        name="predict"):
        """Route ``/predict`` through a :class:`MicroBatcher` (started
        with the server).  Call before :meth:`start`.  ``name`` labels
        this engine's metrics row — give each server its own when
        several batched servers share one process (same-name engines
        replace each other in the /metrics registry: the RESTART
        semantics)."""
        from veles_tpu.serving import MicroBatcher
        from veles_tpu.serving import metrics as metrics_mod
        if sample_shape is None:
            sample_shape = self._infer_sample_shape()
        # a FRESH registered instance per enable: a (re)started server
        # must start its counters at zero, not atop the previous run's
        m = metrics or metrics_mod.new(name)
        self.batcher = MicroBatcher(
            self._ensure_forward(), max_batch=max_batch,
            queue_depth=queue_depth, batch_wait_s=batch_wait_s,
            deadline_s=deadline_s, sample_shape=sample_shape,
            metrics=m, name=name, faults=self.faults,
            tracer=self.tracer)
        self.metrics = m
        return self

    def predict(self, batch):
        x = numpy.asarray(batch, numpy.float32)
        if self.normalizer is not None:
            x = self.normalizer.apply(x)
        if self.batcher is not None:
            probs = self.batcher.submit(x)
        else:
            probs = self._ensure_forward()(x)
        return {"output": probs.tolist(),
                "argmax": probs.reshape(len(probs), -1)
                               .argmax(axis=1).tolist()}

    # ---------------------------------------------------------------- server
    def start(self, host="127.0.0.1", port=8180):
        from veles_tpu.serving.batcher import DeadlineExceeded, Overloaded
        api = self
        if self.batcher is not None:
            self.batcher.start()

        class Handler(BaseHTTPRequestHandler):
            def _drain(self, length, cap=64 << 20):
                """Discard an unread request body (bounded) before an
                early error reply — closing with bytes still in flight
                RSTs the connection and the client never sees the
                structured error it was owed."""
                left = min(length, cap)
                while left > 0:
                    chunk = self.rfile.read(min(left, 1 << 16))
                    if not chunk:
                        return
                    left -= len(chunk)

            def _reply(self, code, payload, headers=()):
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                split = urllib.parse.urlsplit(self.path)
                path = split.path.rstrip("/")
                if path == "/metrics.json" and api.metrics is not None:
                    self._reply(200, api.metrics.snapshot())
                elif path == "/timeseries.json" \
                        and api.telemetry is not None:
                    # continuous telemetry (ISSUE 14): every metrics
                    # family's windowed rates/gauges/percentiles plus
                    # raw ring points — ?window=S trims the window
                    query = urllib.parse.parse_qs(split.query)
                    window = 60.0
                    try:
                        if query.get("window"):
                            window = float(query["window"][0])
                            # not (window > 0) also catches NaN —
                            # 'nan <= 0' is False, and a NaN window
                            # would serialize as a non-strict literal
                            if not (window > 0) \
                                    or window == float("inf"):
                                raise ValueError
                    except ValueError:
                        self._reply(400, {"error": "window must be a "
                                          "positive number of "
                                          "seconds"})
                        return
                    self._reply(200, api.telemetry.snapshot(
                        window_s=window))
                elif path == "/slo.json" and api.slo is not None:
                    # burn-rate objectives (ISSUE 14)
                    self._reply(200, api.slo.snapshot())
                elif path == "/ledger.json" and api.tracer is not None:
                    # the LIVE per-op cost ledger (ISSUE 14): the same
                    # dedup-by-dispatch-id rows tools/trace_report.py
                    # aggregates, maintained incrementally in-process
                    from veles_tpu.serving.metrics import \
                        monotonic_offset
                    rows = api.tracer.live_ledger()
                    self._reply(200, {
                        "sampled_at": round(monotonic_offset(), 6),
                        "dispatches_total": sum(r["dispatches"]
                                                for r in rows),
                        "rows": rows})
                elif path == "/status":
                    # the human panel (ISSUE 14): plain text, curl-able
                    body = render_status(
                        metrics=api.metrics, telemetry=api.telemetry,
                        slo=api.slo, tracer=api.tracer).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif path == "/trace.json" and api.tracer is not None:
                    # the flight recorder as Chrome-trace/Perfetto JSON
                    # (ISSUE 12): ?last=N trims to the newest N
                    # requests; load at ui.perfetto.dev
                    query = urllib.parse.parse_qs(split.query)
                    last = None
                    try:
                        if query.get("last"):
                            last = int(query["last"][0])
                    except ValueError:
                        self._reply(400, {"error": "last must be an "
                                          "integer"})
                        return
                    self._reply(200, api.tracer.export_chrome(
                        last=last))
                elif path == "/metrics":
                    from veles_tpu.serving import metrics as metrics_mod
                    # merge this server's instance into the registry
                    # render (one # TYPE line per family) even when a
                    # later engine evicted it from the registry
                    instances = metrics_mod.registered()
                    if api.metrics is not None \
                            and api.metrics not in instances:
                        instances.append(api.metrics)
                    body = metrics_mod.render_instances(instances).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply(404, {"error": "unknown path %r"
                                      % self.path})

            def do_POST(self):
                # request-id stamping (ISSUE 12 satellite): echo the
                # client's X-Request-Id (or mint one) on EVERY reply —
                # success and structured error — so client logs,
                # traces, and load_gen records join on one key
                t0 = time.monotonic()
                rid = (self.headers.get("X-Request-Id") or "").strip()
                rid = rid[:64] or uuid.uuid4().hex[:16]
                ctx = None
                if api.tracer is not None:
                    ctx = api.tracer.start_request(
                        rid=rid, name="http.request", cat="http",
                        attrs={"path": self.path})
                #: set by _handle_post's DeadlineExceeded branch — a
                #: 503 alone is not proof of a deadline (injected
                #: transient 503s are not sheds)
                self._shed = False
                code, payload, headers = 500, {"error": "internal"}, []
                try:
                    if api.tracer is not None:
                        from veles_tpu.serving import tracing
                        # ctx None = the sampler skipped this request:
                        # bind the sentinel so the router/engine below
                        # do not re-roll and root partial trees
                        with tracing.use(ctx if ctx is not None
                                         else tracing.SAMPLED_OUT):
                            code, payload, headers = \
                                self._handle_post(t0)
                    else:
                        code, payload, headers = self._handle_post(t0)
                finally:
                    if ctx is not None:
                        # 5xx replies dump the flight recorder; only a
                        # real DeadlineExceeded is the deadline-blown
                        # shape (an injected transient 503 is not)
                        api.tracer.finish_request(
                            ctx,
                            error=("http %d" % code) if code >= 500
                            else None,
                            deadline=self._shed,
                            attrs={"status": code})
                if isinstance(payload, dict):
                    payload.setdefault("request_id", rid)
                self._reply(code, payload,
                            list(headers) + [("X-Request-Id", rid)])

            def _handle_post(self, t0):
                """Run one POST; returns (code, json_payload, headers)
                — the reply itself (request-id stamp, trace-root
                closure) happens in do_POST."""
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    return 400, {"error": "malformed "
                                 "Content-Length header"}, []
                if self.path.rstrip("/") != "/predict":
                    self._drain(length)
                    return 404, {"error": "unknown path %r — POST "
                                 "/predict" % self.path}, []
                if length > api.max_body:
                    self._drain(length)
                    return 413, {
                        "error": "request body %d bytes exceeds the "
                                 "%d limit" % (length, api.max_body)}, []
                try:    # parse: malformed payloads are 400, full stop
                    payload = json.loads(self.rfile.read(length))
                    batch = payload["input"]     # both flows require it
                except (json.JSONDecodeError, KeyError, TypeError) as e:
                    return 400, {"error": "%s: %s"
                                 % (type(e).__name__, e)}, []
                if api.faults is not None:
                    from veles_tpu.serving.faults import InjectedHTTPError
                    try:
                        api.faults.fire("http.request")
                    except InjectedHTTPError as e:
                        # a transient HTTP-level fault: structured
                        # reply at the injected status, Retry-After on
                        # the retryable codes — the shape load_gen's
                        # failure classes and the chaos harness assert
                        headers = []
                        if e.code in (429, 503):
                            headers = [("Retry-After", "%d" % max(
                                1, int(e.retry_after + 0.999)))]
                        return e.code, {
                            "error": str(e),
                            "retry_after": e.retry_after}, headers
                try:    # dispatch
                    result = (api._handler(payload)
                              if api._handler is not None
                              else api.predict(batch))
                except Overloaded as e:
                    # Retry-After is integer delta-seconds per RFC 9110
                    # (the exact float rides in the JSON body)
                    return 429, {"error": str(e),
                                 "retry_after": e.retry_after}, \
                        [("Retry-After", "%d" % max(
                            1, int(e.retry_after + 0.999)))]
                except DeadlineExceeded as e:
                    self._shed = True
                    return 503, {"error": str(e)}, [("Retry-After",
                                                     "1")]
                except (TypeError, ValueError) as e:
                    # input-validation contract: shape/range/length
                    # complaints raised while processing the payload
                    # (batcher shape check, serve_lm prompt bounds, bad
                    # knob types) are the CLIENT's error
                    return 400, {"error": "%s: %s"
                                 % (type(e).__name__, e)}, []
                except Exception as e:   # noqa: BLE001 — server fault
                    if api.metrics is not None:
                        api.metrics.record_error()
                    api.warning("request failed: %s", e)
                    return 500, {"error": "%s: %s"
                                 % (type(e).__name__, e)}, []
                if api.metrics is not None:
                    api.metrics.record_response(time.monotonic() - t0)
                return 200, result, []

            def log_message(self, fmt, *args):
                api.debug("restful: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.port = self._server.server_address[1]
        self.info("REST serving on http://%s:%d/predict", host, self.port)
        return self

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self.telemetry is not None:
            # the sampler reads engine metrics: stop it before the
            # engines so a mid-shutdown tick never races a teardown
            self.telemetry.stop()
        if self.batcher is not None:
            self.batcher.stop()
        if self.model_manager is not None:
            # the publisher must stop BEFORE the fleet it deploys to
            self.model_manager.stop()
            self.model_manager = None
        if self.health_checker is not None:
            # the prober must stop BEFORE its engines do, or its next
            # probe lands on a stopped engine and counts a fake failure
            self.health_checker.stop()
            self.health_checker = None
        if self.lm_engine is not None:
            self.lm_engine.stop()


def render_status(metrics=None, telemetry=None, slo=None, tracer=None,
                  window_s=60.0):
    """The ``GET /status`` text panel (ISSUE 14): the operator's
    one-glance view — live gauges, windowed rates and tail latency
    from the telemetry store, every SLO objective's state and burn,
    and the top live-ledger rows.  Plain text by design: readable in
    a terminal over curl, no client tooling required."""
    from veles_tpu.serving.metrics import monotonic_offset
    lines = ["veles_tpu serving status",
             "sampled_at %.3fs (monotonic offset)"
             % monotonic_offset(), ""]
    if metrics is not None:
        snap = metrics.snapshot()
        lines.append("[engine %s]" % snap["name"])
        lines.append(
            "  requests %d  responses %d  errors %d  429 %d  shed %d"
            % (snap["requests"], snap["responses"], snap["errors"],
               snap["rejected"], snap["shed"]))
        g = snap["gauges"]
        lines.append(
            "  queue_depth %g  slots %g/%g  kv_pages_free %g/%g  "
            "compile_programs %g"
            % (g.get("queue_depth", 0), g.get("slots_busy", 0),
               g.get("slots_total", 0), g.get("kv_pages_free", 0),
               g.get("kv_pages_total", 0),
               g.get("compile_programs", 0)))
        lines.append(
            "  ewma ttft %.4fs  decode_step %.4fs  mfu_live %s"
            % (snap["ewma"].get("ttft", 0.0),
               snap["ewma"].get("decode_step", 0.0),
               g.get("mfu_live", "-")))
        lines.append("")
    if telemetry is not None:
        lines.append("[telemetry — last %gs of %d samples @ %gs]"
                     % (window_s, telemetry.samples,
                        telemetry.interval_s))
        for key in telemetry.sources():
            rq = telemetry.window("%s.counter.responses" % key,
                                  window_s)
            er = telemetry.window("%s.counter.errors" % key, window_s)
            tt = telemetry.window("%s.hist.ttft" % key, window_s)
            ds = telemetry.window("%s.hist.decode_step" % key,
                                  window_s)
            lines.append(
                "  %-24s %7.2f resp/s  %5.2f err/s  "
                "ttft p95 %ss  step p95 %ss"
                % (key,
                   rq["rate_per_s"] if rq else 0.0,
                   er["rate_per_s"] if er else 0.0,
                   tt["p95"] if tt else "-",
                   ds["p95"] if ds else "-"))
        lines.append("")
    if slo is not None:
        snap = slo.snapshot()
        lines.append("[slo — worst state: %s, %d page(s) total]"
                     % (snap["worst_state_name"],
                        snap["pages_total"]))
        for row in snap["objectives"]:
            burns = " ".join("%gs=%.2fx" % (b["window_s"], b["burn"])
                             for b in row["burn_rates"])
            lines.append("  %-5s %-24s %-12s target %g  burn %s"
                         % (row["state_name"].upper(), row["source"],
                            row["objective"], row["target"], burns))
        lines.append("")
    if tracer is not None:
        rows = tracer.live_ledger()
        lines.append("[cost ledger — %d dispatch(es), top rows]"
                     % sum(r["dispatches"] for r in rows))
        for r in rows[:8]:
            lines.append(
                "  %-18s bucket %-6s %-8s n=%-7d p50 %8.3fms  "
                "p95 %8.3fms  total %10.1fms"
                % (r["op"], r["bucket"], r["backend"],
                   r["dispatches"], r["p50_ms"], r["p95_ms"],
                   r["total_ms"]))
        lines.append("")
    return "\n".join(lines) + "\n"


def serve_lm(workflow, host="127.0.0.1", port=8180, max_new=256,
             slots=0, queue_depth=64, deadline_s=30.0,
             prefix_cache=0, prefill_chunk=0, spec_k=0,
             queue_tokens=0, paged_kv=0, attn_kernel=None,
             megastep=0, tp=0, replicas=1, router="metrics",
             health=False, health_interval_s=1.0, hedge=0.0,
             retries=0, fault_plan=None, model_dir=None,
             publish_interval_s=5.0, canary=1, canary_watch_s=2.0,
             auto_rollback=True, trace=None, trace_last=256,
             telemetry=0.0, slo=None):
    """Serve a trained transformer-trainer workflow (e.g. char_lm) for
    autoregressive continuation: POST ``{"input": [[tok, ...]],
    "n_new": N, "temperature": T, "top_k": K, "seed": S}`` to
    ``/predict`` returns ``{"tokens": [[...]]}`` — prompt plus
    continuation per row.

    ``slots > 0`` starts a :class:`veles_tpu.serving.LMEngine` and
    routes GREEDY requests (temperature 0, the default) through
    slot-based continuous batching: concurrent prompts decode side by
    side over one shared KV cache, each request gets its exact
    ``n_new`` (no tier overshoot), and output is bit-identical to the
    direct path.  Sampled requests (temperature > 0) always take the
    direct path below.

    The LM serving FAST PATH (ISSUE 4) rides on the engine:
    ``prefix_cache=N`` caches N chunks of prompt KV in a radix trie
    (shared system prompts prefill once), ``prefill_chunk=C`` runs
    prompts as C-token chunks interleaved with decode, ``spec_k=K``
    enables prompt-lookup speculative decoding (several tokens per
    dispatch on repetitive text), ``queue_tokens=T`` budgets admission
    by queued prompt tokens, and ``paged_kv=N`` (ISSUE 6) switches KV
    storage to N fixed-size pages (page = ``prefill_chunk`` tokens,
    requires ``max_len`` divisible by it; ``True`` sizes the pool to
    the contiguous footprint) behind per-lane page tables — lanes
    reserve only their own span, prefix hits are zero-copy page
    references with copy-on-write, and a request the pool cannot place
    queues or sheds (429/503) instead of wedging.
    ``attn_kernel='auto'`` (ISSUE 7) swaps the paged engine's
    attention for the Pallas flash-decode / fused-prefill kernels on
    real TPU hardware, with an automatic XLA fallback (off-TPU or
    unsupported geometry — logged once, counted on ``/metrics`` as
    ``attn_kernel_fallbacks``); ``'force'`` insists off-TPU (interpret
    mode, test gear); ``None`` follows
    ``attention.set_attention_backend('flash_serve')``.
    ``megastep=K`` (ISSUE 13) fuses K decode iterations — propose →
    verify → accept legs when ``spec_k`` is on — into one jitted
    ``lax.scan`` dispatch per engine tick: admission, deadline
    shedding, completion detection, weight-swap application and
    tracing all move to MEGASTEP BOUNDARIES (a deadline expiring
    mid-megastep sheds at the next boundary; see USAGE.md "Megastep
    decode").  All preserve bit-identical greedy output; see
    ``veles_tpu/serving/lm_engine.py``.

    SHARDED SERVING (ISSUE 8): ``tp=N`` runs each engine's decode
    tensor-parallel over an N-device mesh (weights head-sharded,
    KV head-wise — greedy output still bit-identical); ``replicas=R``
    builds R independent engines (each on its own device slice —
    ``R×max(tp,1)`` devices when tp >= 2) behind a
    :class:`veles_tpu.serving.Router` placing each request by live
    metrics signals (``router='metrics'``; ``'round_robin'`` for the
    skew baseline).  Routed responses carry a per-row ``"replicas"``
    list so closed-loop clients (``tools/load_gen.py --lm``) can
    measure balance; ``/metrics`` renders per-replica
    ``{replica="i"}`` labeled families and ``/metrics.json`` embeds
    every replica snapshot.  Admission (429/503) is unchanged behind
    the router.

    The RESILIENCE layer (ISSUE 10, all default-off): ``retries=N``
    re-places a request whose replica FAULTED (not sheds, not client
    errors) on a different replica with exponential jittered backoff;
    ``hedge=T`` duplicates a request outstanding past T seconds (T<0:
    1.5× the live latency p95) on a second replica, first complete
    wins, loser cancelled; ``health=True`` starts a
    :class:`veles_tpu.serving.HealthChecker` that auto-quarantines a
    wedged/failing replica through the router's drain path and
    re-admits it after a cooldown (half-open circuit breaker;
    ``replica_health_state`` / ``circuit_open_total`` on /metrics).
    Any of the three wraps a single replica in the bit-identical
    degenerate router.  ``fault_plan`` attaches a
    :class:`veles_tpu.serving.FaultPlan` (CLI ``--fault-plan FILE``)
    arming the deterministic fault-injection sites — test/chaos gear,
    never armed in production.  See USAGE.md "Failure semantics".

    ZERO-DOWNTIME WEIGHT UPDATES (ISSUE 11): ``model_dir=DIR`` starts
    a :class:`veles_tpu.serving.ModelManager` publisher loop watching
    DIR for the snapshotter's ``*_current.*`` checkpoints every
    ``publish_interval_s`` seconds — each new file is validated and
    loaded OFF the hot path, then rolled across the fleet via
    ``Router.deploy``: ``canary=N`` replicas swap and answer a
    parity probe first, traffic steers at them for ``canary_watch_s``
    seconds while the deploy watches the live health signals (0
    reduces the watch to one instantaneous signal check), and a bad
    canary auto-rolls back
    (``auto_rollback=False`` leaves the mixed fleet for the operator).
    In-flight requests finish on the weights they started on; every
    engine-path reply carries a per-row ``"weights_version"`` stamp
    so clients can observe the cutover (``tools/load_gen.py --lm``
    aggregates it).  See USAGE.md "Zero-downtime weight updates".

    REQUEST TRACING (ISSUE 12): ``trace='all'|'errors'|'sample:P'``
    arms a :class:`veles_tpu.serving.SpanTracer` threaded through the
    whole request path — HTTP root span, router attempt spans, queue
    wait, every prefill chunk / decode tick / speculative verify / COW
    copy, with device dispatches fenced so durations are device wall
    time.  The last ``trace_last`` finished requests stay
    reconstructable in a flight-recorder ring (errored/deadline-blown
    requests are auto-dumped as waterfall text), ``GET
    /trace.json?last=N`` exports Chrome-trace/Perfetto JSON, and
    ``tools/trace_report.py`` renders waterfalls + the per-op cost
    ledger.  Default off: every site is one attribute-is-None check
    (the ``faults.py`` discipline; the chaos bench pins unarmed
    overhead <2%% of a decode step).  Every JSON reply (success and
    error) is stamped with a ``request_id`` echoed from the
    ``X-Request-Id`` header or generated server-side, whether or not
    tracing is armed.

    CONTINUOUS TELEMETRY + SLOs (ISSUE 14, engine path only):
    ``telemetry=S`` starts a
    :class:`veles_tpu.serving.TimeSeriesStore` sampling every engine
    (and router) metrics family into bounded rings every ``S``
    seconds (``True`` = 1 s) — counters become windowed rates, gauges
    keep min/max/mean, histogram deltas resolve windowed p50/p95 —
    plus per-engine runtime gauges (live jit ``compile_programs`` +
    ``compiles_total``, process RSS, device memory where reported,
    ``mfu_live`` from the lm_bench FLOPs model, megastep waste
    fraction), served at ``GET /timeseries.json?window=S``.
    ``slo=`` (a JSON objective file path, a parsed spec dict, or
    ``True`` for the stock objectives) arms a
    :class:`veles_tpu.serving.SLOMonitor` riding the store's tick:
    multi-window error-budget burn rates per objective per replica,
    ok→warn→page state machine at ``GET /slo.json``, and — when
    ``health=True`` — a page-level burn on ONE replica feeds the
    HealthChecker (``note_slo_page``) toward the same quarantine path
    a failed probe takes.  ``slo`` implies ``telemetry`` (default
    1 s).  A traced server additionally serves the LIVE per-op cost
    ledger at ``GET /ledger.json`` (same dedup rules as
    ``tools/trace_report.py``, no export round trip), and every
    server serves the human-readable ``GET /status`` text panel.
    The hot path has zero telemetry sites: the store samples on its
    own thread (the pull model) — overhead is bounded by the chaos
    bench's ``fault_free_overhead`` leg (<1%% of a decode step
    together with the incremental ledger).

    The direct path decodes one prompt batch at a time via the
    KV-cached ``transformer.generate``, one jitted dispatch per
    request.  Compile count and per-request cost are both BOUNDED
    against adversarial or merely varied clients:

    - prompt lengths are BUCKETED — the prompt is right-padded to the
      next power of two and decoded with a traced ``true_len`` (bit-exact
      under causal attention, see ``transformer._generate_impl``), so
      compiles grow with log2(max_len), not with every distinct prompt
      length;
    - ``n_new`` is quantized into a few static TIERS (clamped to
      ``max_new``), so an n_new=1 request pays a short tier's decode,
      not the full ``max_new``, while per-value recompiles stay
      impossible.  top_k remains jit-static but vocab-bounded.
    """
    from veles_tpu.ops.transformer import trainer_sample_tokens
    trainer = workflow.trainer
    # marshalled ONCE (params are frozen while serving; pipelined
    # trainers pay the block unstack here, not per request)
    params = trainer._to_portable(trainer.params)
    cache_len = int(trainer.max_len)
    # geometric ladder bounds BOTH the compile count (one generate
    # program per tier) and the decode overshoot (≤4× the requested
    # n_new; {8,32,max} alone made an n_new=40 request pay a full
    # max_new=256 decode)
    tiers = sorted({t for t in (8, 32, 128, max_new) if t <= max_new})
    from veles_tpu.serving.tracing import SpanTracer
    tracer = SpanTracer.from_spec(trace, last=int(trace_last))
    engine = None
    checker = None
    manager = None
    routed = False
    if slots > 0:
        from veles_tpu.serving import (HealthChecker, LMEngine,
                                       ModelManager, Router,
                                       RouterMetrics,
                                       replica_device_slices)
        from veles_tpu.serving import metrics as metrics_mod
        n_rep = max(1, int(replicas))
        tp_n = int(tp or 0)
        # the RESILIENCE layer (ISSUE 10) lives on the Router — a
        # single replica wraps in the (bit-identical) degenerate
        # router when health/hedge/retries are requested; the
        # publisher loop (ISSUE 11) deploys through the router too
        resilient = bool(health) or bool(hedge) or int(retries) > 0 \
            or bool(model_dir)
        slices = (replica_device_slices(n_rep, tp_n)
                  if n_rep > 1 else None)

        def build_engine(i=None):
            """One engine — replica ``i`` owns its own device slice
            (replica_device_slices — the same mapping the bench
            measures) and a metrics row labeled {replica="i"} under
            the shared 'lm' family."""
            devices = None
            label = None
            eng_name = "lm"
            if i is not None:
                devices = slices[i]
                label = {"replica": str(i)}
                eng_name = "lm_r%d" % i
            return LMEngine(
                params, n_heads=trainer.n_heads, max_len=cache_len,
                slots=slots, rope=getattr(trainer, "rope", False),
                window=getattr(trainer, "window", None),
                sinks=getattr(trainer, "attn_sinks", 0),
                queue_depth=queue_depth, deadline_s=deadline_s,
                prefix_cache=prefix_cache, prefill_chunk=prefill_chunk,
                spec_k=spec_k, queue_tokens=queue_tokens,
                paged_kv=paged_kv, attn_kernel=attn_kernel,
                megastep=megastep,
                tp=tp_n, devices=devices, name=eng_name,
                metrics=metrics_mod.new("lm", labels=label),
                faults=fault_plan, tracer=tracer)

        if slo and not telemetry:
            telemetry = 1.0         # objectives need the store
        if n_rep > 1 or resilient:
            routed = True
            engine = Router(
                [build_engine(i if n_rep > 1 else None)
                 for i in range(n_rep)],
                metrics=metrics_mod.register(RouterMetrics("lm_router")),
                policy=router, retries=int(retries),
                hedge_after_s=float(hedge or 0.0),
                faults=fault_plan, tracer=tracer).start()
            if health:
                checker = HealthChecker(
                    engine, interval_s=float(health_interval_s),
                    probe_timeout_s=max(5.0, deadline_s / 2)).start()
            if model_dir:
                manager = ModelManager(
                    engine, model_dir,
                    interval_s=float(publish_interval_s),
                    canary=int(canary),
                    watch_s=float(canary_watch_s),
                    auto_rollback=bool(auto_rollback)).start()
        else:
            engine = build_engine().start()

    store = None
    monitor = None
    if engine is not None and telemetry:
        from veles_tpu.serving import timeseries as ts_mod
        from veles_tpu.serving.metrics import _registry_key
        interval = 1.0 if telemetry is True else float(telemetry)
        store = ts_mod.telemetry_for(engine, interval_s=interval)
        if slo:
            from veles_tpu.serving.slo import SLOMonitor
            replica_engines = getattr(engine, "replicas", [engine])
            source_replicas = {
                _registry_key(e.metrics): i
                for i, e in enumerate(replica_engines)}
            # SLO gauges/counters land in the router's (or the solo
            # engine's) own family, so /metrics carries slo_state too
            kw = dict(checker=checker,
                      source_replicas=source_replicas,
                      metrics=engine.metrics)
            if slo is True:
                monitor = SLOMonitor(
                    store, SLOMonitor.default_objectives(), **kw)
            else:
                monitor = SLOMonitor.from_spec(slo, store, **kw)
            # the monitor rides the store's tick: one evaluation per
            # sampling window, deterministic under sample_once()
            store.add_listener(monitor.sample_once)
        ts_mod.set_default(store)
        store.start()

    def handler(request):
        prompt = numpy.asarray(request["input"], numpy.int32)
        want = min(int(request.get("n_new", 32)), max_new)
        if want < 1:        # n_new=0: echo/validation probe, no decode
            return {"tokens": prompt.tolist()}
        s_true = prompt.shape[1]
        headroom = cache_len - s_true
        if headroom < 1:
            raise ValueError("prompt length %d leaves no room to decode "
                             "(max_len %d)" % (s_true, cache_len))
        temperature = float(request.get("temperature", 0.0))
        # speculative decoding needs spec_k cache positions of write
        # headroom; a prompt too close to the cache cap falls back to
        # the direct path instead of being refused
        eng_headroom = headroom - (engine.spec_k if engine is not None
                                   else 0)
        if engine is not None and temperature == 0.0 \
                and eng_headroom >= 1:
            # continuous batching: exact n_new (no tier), concurrent
            # prompts share the decode step across slots
            if routed:
                toks, reps, vers = engine.generate(
                    prompt, min(want, eng_headroom),
                    return_replicas=True, return_versions=True)
                # per-row replica ids and weights_version stamps: the
                # client-side balance and swap-cutover evidence
                # load_gen --lm aggregates
                return {"tokens": toks.tolist(), "replicas": reps,
                        "weights_version": vers}
            toks, vers = engine.generate(
                prompt, min(want, eng_headroom), return_versions=True)
            return {"tokens": toks.tolist(), "weights_version": vers}
        # decode length: round the request UP to a tier; near the cache
        # cap fall back to the largest tier that fits (or the exact
        # headroom when even the smallest doesn't — rare, self-limiting)
        run = next((t for t in tiers if t >= want), tiers[-1])
        if run > headroom:
            fitting = [t for t in tiers if t <= headroom]
            run = fitting[-1] if fitting else headroom
        # prompt bucket: right-pad to the next power of two that still
        # fits the cache; true_len keeps decoding bit-exact
        bucket = 16
        while bucket < s_true:
            bucket *= 2
        bucket = min(bucket, cache_len - run)
        if bucket > s_true:
            prompt = numpy.pad(prompt, ((0, 0), (0, bucket - s_true)))
        top_k = request.get("top_k")
        out = trainer_sample_tokens(
            trainer, prompt, n_new=run,
            temperature=temperature,
            seed=int(request.get("seed", 0)), params=params,
            max_len=cache_len,
            top_k=int(top_k) if top_k is not None else None,
            true_len=s_true)
        # the continuation lands after the PADDED width; reply with the
        # true prompt plus min(want, run) new tokens
        new = out[:, prompt.shape[1]:prompt.shape[1] + min(want, run)]
        return {"tokens": numpy.concatenate(
            [out[:, :s_true], new], axis=1).tolist()}

    api = RESTfulAPI(None, handler=handler,
                     metrics=engine.metrics if engine is not None
                     else None, faults=fault_plan, tracer=tracer,
                     telemetry=store, slo=monitor)
    api.lm_engine = engine
    api.health_checker = checker
    api.model_manager = manager
    return api.start(host=host, port=port)


def serve_artifact(path, host="127.0.0.1", port=8180, max_batch=0):
    """Serve a StableHLO export artifact (veles_tpu.export) WITHOUT
    constructing any training workflow — the libVeles serving path
    (SURVEY §2.4/§3.4): load weights + compiled forward, start HTTP.
    ``max_batch > 0`` coalesces concurrent requests through the
    micro-batcher (the artifact's symbolic batch dim makes every bucket
    a warm program)."""
    from veles_tpu.export import load_model
    model = load_model(path)
    api = RESTfulAPI(None, forward=model.predict)
    if max_batch > 0:
        api.enable_batching(
            max_batch=max_batch,
            sample_shape=tuple(model.manifest["input_sample_shape"]))
    return api.start(host=host, port=port)


def serve_snapshot(path, host="127.0.0.1", port=8180, build=None):
    """CLI helper: restore a snapshot into a rebuilt workflow and serve it.

    ``build`` is a zero-arg callable returning the (initialized) workflow —
    usually a sample's ``build`` + ``initialize``; the snapshot then restores
    the trained weights (SURVEY §3.3/§3.4 snapshot-is-the-artifact flow).
    """
    from veles_tpu import snapshotter
    wf = build()
    snapshotter.restore(wf, path)
    return RESTfulAPI(wf).start(host=host, port=port)
