"""NN-specific plotting units.

Ref: veles/znicz/nn_plotting_units.py::Weights2D/KohonenHits/MSEHistogram
[H] (SURVEY §2.3).
"""

from __future__ import annotations

import numpy

from veles_tpu.plotter import Plotter


class Weights2D(Plotter):
    """First-layer weights as a grid of images.

    Link ``input`` to a forward unit; its (n_in, n_out) weights are
    transposed and reshaped to ``sample_shape`` (inferred square when not
    given), up to ``limit`` images.
    """

    def __init__(self, workflow, sample_shape=None, limit=64, **kwargs):
        super().__init__(workflow, **kwargs)
        self.sample_shape = sample_shape
        self.limit = int(limit)

    def plot_spec(self):
        weights = self.input.weights.to_numpy()
        if weights.ndim == 4:       # conv HWIO -> one image per kernel
            imgs = numpy.moveaxis(weights, -1, 0)
            if imgs.shape[-1] not in (1, 3):
                imgs = imgs[..., :1]
        else:                       # dense (n_in, n_out) -> per-output row
            w = weights.T[:self.limit]
            shape = self.sample_shape
            if shape is None:
                side = int(round(w.shape[1] ** 0.5))
                if side * side != w.shape[1]:
                    return None
                shape = (side, side)
            imgs = w.reshape(len(w), *shape)
        return {"kind": "image_grid", "images": imgs[:self.limit],
                "title": "%s weights" % self.input.name}


class KohonenHits(Plotter):
    """SOM win-count map.  Link ``input`` to a KohonenForward."""

    def plot_spec(self):
        hits = numpy.asarray(self.input.hits)
        trainer = getattr(self, "trainer", None)
        shape = trainer.shape if trainer is not None else (
            int(round(len(hits) ** 0.5)),) * 2
        return {"kind": "matrix", "matrix": hits.reshape(shape),
                "cmap": "hot", "title": "SOM hits"}


class MSEHistogram(Plotter):
    """Distribution of per-sample reconstruction errors.

    Link ``input`` to an EvaluatorMSE (uses err_output per-sample norms).
    """

    def __init__(self, workflow, bins=30, **kwargs):
        super().__init__(workflow, **kwargs)
        self.bins = bins

    def plot_spec(self):
        err = self.input.err_output.to_numpy()
        if err is None:
            return None
        per_sample = numpy.sqrt(
            (err.reshape(len(err), -1) ** 2).sum(axis=1))
        return {"kind": "hist", "values": per_sample, "bins": self.bins,
                "title": "per-sample RMSE"}
