"""AcceleratedUnit — base class for device-compute units.

Ref: veles/accelerated_units.py::AcceleratedUnit [H] (SURVEY §2.1).  The
reference assembled OpenCL/CUDA source with #define dictionaries, built
programs into a binary cache, and dispatched ``ocl_run/cuda_run/numpy_run``
per backend.  TPU-native replacement: each unit exposes pure functions from
``veles_tpu.ops.functional`` and jits them once at initialize time — XLA's
compilation cache is the binary cache, jit is the program build, and there is
exactly ONE backend (the numpy oracle lives in the tests, as the reference's
numpy backend effectively did — SURVEY §4).
"""

from __future__ import annotations

from veles_tpu.units import Unit


class AcceleratedUnit(Unit):
    """A unit whose ``run`` dispatches jitted device computations."""

    def __init__(self, workflow, dtype=None, **kwargs):
        super().__init__(workflow, **kwargs)
        import numpy
        self.dtype = numpy.dtype(dtype or "float32")
        self._jitted = {}

    def jit(self, name, fn, **jit_kwargs):
        """Jit ``fn`` once per unit under ``name`` (idempotent)."""
        import jax
        cached = self._jitted.get(name)
        if cached is None:
            cached = jax.jit(fn, **jit_kwargs)
            self._jitted[name] = cached
        return cached


class AcceleratedWorkflow:
    """Marker mixin for workflows that own device state.

    Ref: veles/accelerated_units.py::AcceleratedWorkflow [H].  Under XLA there
    is no per-workflow device context to manage, so this only tags the class;
    kept for API parity.
    """
