"""Workflow — the Unit container and host-side graph scheduler.

Ref: veles/workflow.py::Workflow/StartPoint/EndPoint/Repeater [H] and
veles/thread_pool.py::ThreadPool [H] (SURVEY §2.1).

Scheduler design note (TPU-first, not a port): the reference executed each
``Unit.run`` on a Twisted thread pool, but the graph's control edges serialize
the critical path anyway (SURVEY §3.1).  On TPU all heavy work happens inside
asynchronously-dispatched XLA computations, so a deterministic sequential
event loop on the host is both simpler and faster (no GIL ping-pong): the
host thread races ahead queueing device work while XLA executes.  The hot
cycle additionally gets a fused compiled path (one jitted train_step traced
from the unit chain) used by the standard workflows; this event loop is the
general scheduler every graph (including arbitrary user graphs) runs under.
"""

from __future__ import annotations

import time
from collections import deque

from veles_tpu.units import Unit, TrivialUnit


class StartPoint(TrivialUnit):
    """The unique entry node; firing it starts the graph."""


class EndPoint(TrivialUnit):
    """The unique exit node; running it finishes the workflow."""

    def run(self):
        if self.workflow is not None:
            self.workflow.on_end_point()


class Repeater(TrivialUnit):
    """Control node that closes the training cycle.

    OR gate semantics: fires when ANY incoming link fires (the start point
    once, then the tail of the backward chain every iteration) — this is what
    makes the loader→forwards→decision→gds cycle loop (ref:
    veles/workflow.py::Repeater [H]).
    """

    def open_gate(self, src):
        for unit in self._links_from:
            self._links_from[unit] = False
        return True


class Workflow(Unit):
    """A Unit that contains units and runs them as a dataflow graph."""

    def __init__(self, workflow=None, name=None, **kwargs):
        self._units = []
        super().__init__(workflow, name=name, **kwargs)
        self.start_point = StartPoint(self, name="start_point")
        self.end_point = EndPoint(self, name="end_point")
        self._stopped = False
        self._finished = False
        self.iteration_limit = kwargs.get("iteration_limit", None)
        self.device = None

    # ------------------------------------------------------------- containers
    @property
    def units(self):
        return list(self._units)

    def add_ref(self, unit):
        if unit not in self._units:
            # Unit names key snapshot state and get_unit lookups, so they
            # must be unique within a workflow; suffix duplicates.
            base = unit.name
            taken = {u.name for u in self._units}
            if base in taken:
                n = 1
                while "%s_%d" % (base, n) in taken:
                    n += 1
                unit.name = "%s_%d" % (base, n)
            self._units.append(unit)
        unit.workflow = self

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)
        unit.workflow = None

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def get_unit(self, name):
        for unit in self._units:
            if unit.name == name:
                return unit
        raise KeyError(name)

    # -------------------------------------------------------------- lifecycle
    def initialize(self, device=None, **kwargs):
        """Initialize every unit.

        Units may raise :class:`DeferredInitError` (or return ``False``) to be
        retried after their producers initialize — mirrors the reference's
        deferred-initialization loop (ref: veles/workflow.py [M]).
        """
        self.device = device
        pending = [u for u in self._units if u is not self]
        for _ in range(len(pending) + 1):
            deferred = []
            for unit in pending:
                try:
                    result = unit.initialize(device=device, **kwargs)
                except DeferredInitError:
                    deferred.append(unit)
                    continue
                if result is False:
                    deferred.append(unit)
            if not deferred:
                break
            if len(deferred) == len(pending):
                raise RuntimeError(
                    "initialization deadlock: %s" %
                    ", ".join(u.name for u in deferred))
            pending = deferred
        super().initialize(device=device, **kwargs)
        return self

    def run(self):
        """Fire the start point and pump the event loop until the end point.

        This is the reference's reactor + thread-pool execution collapsed
        into a deterministic host loop (see module docstring).
        """
        self._stopped = False
        self._finished = False
        iterations = 0
        queue = deque([self.start_point])
        self.start_point.run()
        while queue and not self._stopped and not self._finished:
            unit = queue.popleft()
            for succ in unit.links_to:
                if not succ.open_gate(unit):
                    continue
                if bool(succ.gate_block):
                    continue
                if not bool(succ.gate_skip):
                    begin = time.perf_counter()
                    succ.run()
                    succ.run_time += time.perf_counter() - begin
                    succ.run_count += 1
                if self._stopped or self._finished:
                    break
                queue.append(succ)
            iterations += 1
            if self.iteration_limit and iterations > self.iteration_limit:
                raise RuntimeError("workflow iteration limit exceeded")
        for unit in self._units:
            unit.stop()
        return self

    def on_end_point(self):
        self._finished = True

    def stop(self):
        self._stopped = True

    @property
    def is_finished(self):
        return self._finished

    # -------------------------------------------------------------- reporting
    def print_stats(self):
        """Per-unit wall-time accounting (ref: veles/timeit2.py [M]) plus,
        for fused workflows, the measured DEVICE time of one train step
        (host wall-time per unit cannot see it — dispatch is async)."""
        rows = sorted(self._units, key=lambda u: -u.run_time)
        total = sum(u.run_time for u in self._units)
        self.info("unit run-time breakdown (total %.3fs):", total)
        for unit in rows:
            if unit.run_count == 0:
                continue
            self.info("  %-30s %8d runs %10.3fs", unit.name, unit.run_count,
                      unit.run_time)
        runner = getattr(self, "_fused_runner", None)
        if runner is not None:
            step_time = runner.measure_device_step_time(iters=3)
            if step_time is not None:
                self.info("  fused train step (device)      %10.3f ms/step",
                          step_time * 1e3)
            # release the pinned minibatch (HBM) once measured
            runner._last_train_args = None
        stream = getattr(self, "_stream_stats", None)
        if stream:
            # streaming windowed epoch-scan (epoch_driver.py): did the
            # host keep the device fed?  stall fraction ~0 = staging
            # fully hidden behind compute; ~1 = device starved
            self.info("  streaming: %d windows (%d mb each, stage-ahead "
                      "%d), %d dispatches / %d epochs",
                      stream["windows"], stream["window_minibatches"],
                      stream["stage_ahead"], stream["dispatches"],
                      stream["epochs"])
            self.info("  streaming: %.1f samples/s, staging stall "
                      "%.3fs of %.3fs busy (%.1f%%)",
                      stream["samples_per_sec"],
                      stream["staging_stall_s"],
                      stream["staging_stall_s"] + stream["compute_s"],
                      100.0 * stream["staging_stall_fraction"])

    def graph_data(self):
        """(node_labels, edge_index_pairs) of the unit graph — the one
        structural source both the dot renderer below and the web-status
        SVG view consume."""
        units = list(self._units)
        ids = {u: i for i, u in enumerate(units)}
        edges = [(ids[u], ids[s]) for u in units
                 for s in u.links_to if s in ids]
        return [u.name for u in units], edges

    def generate_graph(self, filename=None):
        """Render the unit graph as graphviz dot text.

        Ref: veles/workflow.py::Workflow.generate_graph [M] — used by docs
        and the web status view.
        """
        nodes, edges = self.graph_data()
        lines = ["digraph %s {" % self.name.replace(" ", "_")]
        for i, label in enumerate(nodes):
            lines.append('  u%d [label="%s"];' % (i, label))
        for src, dst in edges:
            lines.append("  u%d -> u%d;" % (src, dst))
        lines.append("}")
        text = "\n".join(lines)
        if filename:
            with open(filename, "w", encoding="utf-8") as f:
                f.write(text)
        return text

    # --------------------------------------------------------------- snapshot
    def snapshot_state(self):
        """Collect the restorable state of every unit (SURVEY §5.4)."""
        from veles_tpu import prng
        return {
            "workflow_class": type(self).__name__,
            "units": {u.name: u.state_dict() for u in self._units},
            "prng": prng.state_dict(),
        }

    def load_snapshot_state(self, state):
        from veles_tpu import prng
        for name, d in state["units"].items():
            try:
                unit = self.get_unit(name)
            except KeyError:
                self.warning("snapshot has state for unknown unit %r", name)
                continue
            unit.load_state_dict(d)
        prng.load_state_dict(state.get("prng", {}))


class DeferredInitError(Exception):
    """Raised by Unit.initialize to request retry after producers init."""
