"""Epoch-scan CLI training driver — the TPU steady state as the MAIN loop.

The unit-graph event loop (SURVEY §3.1's rebuild) dispatches one fused
step per minibatch; this driver instead runs whole epochs — or k-epoch
chunks — as ONE device program (``FusedRunner.epoch_chunk_eval_fn``),
while keeping the workflow's host-side brains exactly as they are:

- **Decision** sees the same per-epoch summed metrics it accumulates in
  graph mode (validation evaluated BEFORE each epoch's training — the
  loader plans test → validation → train — then the training pass's own
  totals), via the same ``reduce_metrics``/``_on_epoch_end`` methods, so
  improvement tracking, early stopping and logging are identical code.
- **Snapshotter** fires at chunk boundaries through its normal
  ``run()``/``stop()`` gates (the state inside a chunk is not
  addressable — with ``chunk > 1`` snapshot granularity coarsens to the
  chunk, documented).
- **The completion gate artifact is reproduced exactly.**  In graph
  mode, Decision setting ``complete`` gate-skips FusedCommit, so the
  stopping epoch's LAST minibatch update is computed but DISCARDED
  (the reference's ordering — GD units fire after Decision).  The scan
  commits every update, so when completion lands at chunk row R the
  driver replays rows 0..R from the (kept, non-donated) chunk-input
  state with row R truncated to its first ``steps-1`` minibatches —
  one extra dispatch, once per training run.

With no stochastic layers the driver's epoch_metrics and final weights
EQUAL the graph loop's at any chunk size (pinned by
tests/test_launcher.py); dropout networks draw scan-path keys
(documented divergence, same as every epoch-scan path).  Through a
tunnel with ~0.4 s per-execute RPC this is the difference between
minutes and hours (docs/PERF.md round 5).

**Streaming windowed mode** (``--stream-window W``): out-of-core
datasets (RecordsLoader/LMDBLoader) cannot park the whole dataset in
HBM, and used to fall back to one dispatch per minibatch through the
graph loop.  Instead the epoch's minibatch plan is split into contiguous
windows of W minibatches; each window's samples are gathered host-side
(``Loader.gather_window``), uploaded once, and ALL of the window's
minibatches run as one ``lax.scan`` program
(``FusedRunner.window_scan_fn`` — the same ``_step_fn``, so numerics
match the full-batch scan and the graph loop).  While window *i* trains,
a staging thread gathers and uploads window *i+1*
(``--stage-ahead N`` windows in flight) — the RecordsLoader per-minibatch
prefetch generalized to whole windows.  Dispatches per epoch drop from
~minibatches to ~windows, and per-window staging/compute timing feeds
``print_stats`` and the ``/metrics`` gauges (samples/sec, staging-stall
fraction).  The completion-gate artifact is reproduced at window
granularity: the stopping epoch's final window is replayed from its
kept input state with the last minibatch dropped.

Ref: veles/launcher.py + veles/znicz/decision.py [H] — behavior parity
with the reference's epoch bookkeeping, substrate redesigned.
"""

from __future__ import annotations

import time

import numpy

from veles_tpu.logger import Logger
from veles_tpu.loader.base import TRAIN, VALID, TEST

#: minibatches per window when --stream-window is bare/unset on a
#: streaming loader: big enough to amortize the dispatch round-trip,
#: small enough that two windows of typical ImageNet minibatches fit
#: HBM alongside the model
DEFAULT_STREAM_WINDOW = 16


class _WindowStager:
    """Double-buffers training windows for the streaming epoch-scan.

    Pool threads gather up to ``stage_ahead`` windows from the loader's
    backing store (memmap/LMDB pages; the native gather releases the
    GIL) and ``jax.device_put`` them while the device trains the current
    window — the whole-window generalization of RecordsLoader's
    per-minibatch prefetch.  ``take`` blocks until the window is staged;
    the blocked time IS the staging stall the stats report.
    """

    def __init__(self, loader, want_labels, stage_ahead, name="stager"):
        import concurrent.futures
        self.loader = loader
        self.want_labels = want_labels
        self.ahead = max(int(stage_ahead), 1)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.ahead, thread_name_prefix=name)
        self._pending = {}
        self.stall_seconds = 0.0

    def stage(self, gidx, mask):
        """Gather + upload one window NOW (also the pool thread body):
        (x, labels-or-None, window-local idx, mask) device arrays."""
        import jax
        import jax.numpy as jnp
        gidx = numpy.ascontiguousarray(gidx, numpy.int32)
        rows, mb = gidx.shape
        data, labels = self.loader.gather_window(gidx.ravel())
        x = jax.device_put(data)
        y = (jax.device_put(labels)
             if self.want_labels and labels is not None else None)
        lidx = jnp.arange(rows * mb, dtype=jnp.int32).reshape(rows, mb)
        m = jax.device_put(numpy.asarray(mask, numpy.float32))
        return x, y, lidx, m

    def submit(self, key, gidx, mask):
        self._pending[key] = self._pool.submit(self.stage, gidx, mask)

    def take(self, key):
        """The staged window for ``key``, blocking (and accounting the
        block as staging stall) if the gather/upload is still running."""
        fut = self._pending.pop(key)
        begin = time.perf_counter()
        out = fut.result()
        self.stall_seconds += time.perf_counter() - begin
        return out

    def shutdown(self):
        for fut in self._pending.values():
            fut.cancel()
        self._pending.clear()
        self._pool.shutdown(wait=True)


class EpochScanDriver(Logger):
    """Drives a fused StandardWorkflow through epoch-scan chunks
    (HBM-resident datasets) or streamed device-resident windows
    (out-of-core datasets; ``stream_window`` > 0 forces it)."""

    def __init__(self, wf, chunk=1, stream_window=0, stage_ahead=1):
        from veles_tpu.ops.decision import DecisionGD, DecisionMSE
        self.wf = wf
        self.chunk = max(int(chunk), 1)
        self.stream_window = int(stream_window or 0)
        self.stage_ahead = max(int(stage_ahead), 1)
        #: filled by the streaming path: windows, dispatches,
        #: staging-stall/compute seconds, samples/sec (print_stats and
        #: the /metrics gauges read it off the workflow)
        self.stream_stats = None
        runner = getattr(wf, "_fused_runner", None)
        if runner is None:
            raise ValueError("--epoch-scan needs a fused workflow "
                             "(drop --no-fused)")
        loader = wf.loader
        full_batch = (getattr(loader, "original_data", None) is not None
                      and not loader.original_data.is_empty)
        if self.stream_window > 0:
            if not loader.can_gather_windows:
                raise ValueError(
                    "--stream-window needs a loader with gather_window "
                    "(RecordsLoader, LMDBLoader, FullBatchLoader); %s "
                    "has no random-access backing store"
                    % type(loader).__name__)
            self.streaming = True
        elif full_batch:
            self.streaming = False
        elif loader.can_gather_windows:
            # out-of-core loader under bare --epoch-scan: stream with
            # the default window instead of refusing (the pre-streaming
            # behavior) — this is exactly the workload the windowed
            # path exists for
            self.streaming = True
            self.stream_window = DEFAULT_STREAM_WINDOW
        else:
            raise ValueError(
                "--epoch-scan needs a full-batch loader (dataset "
                "resident in device memory) or a window-gatherable "
                "streaming loader (RecordsLoader/LMDBLoader — see "
                "--stream-window); %s is neither" % type(loader).__name__)
        decision = getattr(wf, "decision", None)
        if not isinstance(decision, (DecisionGD, DecisionMSE)):
            raise ValueError(
                "--epoch-scan supports DecisionGD/DecisionMSE workflows; "
                "%r drives training some other way — use the graph loop"
                % type(decision).__name__)
        if not loader.class_lengths[VALID]:
            raise ValueError("--epoch-scan needs a validation set (the "
                             "stopping rule evaluates it per epoch)")
        self.runner = runner
        self.loader = loader
        self.decision = decision

    # ------------------------------------------------------------------ run
    def _feed_decision(self, train_row, val_row, test_row, counts):
        """Hand one epoch's summed metrics to the decision through its
        normal host-side path (reduce_metrics + _on_epoch_end)."""
        dec = self.decision
        n_train, n_valid, n_test = counts

        def host(row, count):
            out = {}
            for key, value in row.items():
                arr = numpy.asarray(value)
                out[key] = float(arr) if arr.ndim == 0 else arr
            out["count"] = count
            return out

        current = {}
        if test_row is not None:
            current["test"] = dec.reduce_metrics(host(test_row, n_test))
        current["validation"] = dec.reduce_metrics(host(val_row, n_valid))
        current["train"] = dec.reduce_metrics(host(train_row, n_train))
        dec._current = current
        dec._on_epoch_end()
        dec._reset_epoch()

    def _notify_reporters(self):
        """Drive any StatusReporter units at epoch/chunk granularity —
        the graph loop runs them off Decision's link; the drivers bypass
        the graph pump, so dashboard/metrics rows are pushed here."""
        from veles_tpu.web_status import StatusReporter
        for unit in getattr(self.wf, "_units", []):
            if isinstance(unit, StatusReporter):
                try:
                    unit.run()
                except Exception as e:   # noqa: BLE001 — never fatal
                    self.warning("status report failed: %s", e)

    def run(self):
        if self.streaming:
            return self._run_streaming()
        return self._run_chunked()

    # ------------------------------------------------- chunked (HBM-resident)
    def _run_chunked(self):
        import jax
        wf = self.wf
        runner, loader, dec = self.runner, self.loader, self.decision
        #: --distributed: the launcher attached a ShardedTrainer — chunks
        #: run under the global mesh (dataset replicated, plan matrices
        #: sharded over 'data', GSPMD all-reduce per step), with the same
        #: host-side flow; metric rows read the local replica
        trainer = getattr(wf, "_sharded_trainer", None)
        if trainer is not None:
            trainer.place_dataset(
                numpy.asarray(loader.original_data.mem),
                None if runner._is_mse
                else numpy.asarray(loader.original_labels.mem))
            data = labels = None        # live in trainer._data/_labels
            fetch = trainer.fetch
        else:
            data = loader.original_data.devmem
            labels = (None if runner._is_mse
                      else loader.original_labels.devmem)
            fetch = lambda tree: jax.tree.map(numpy.asarray, tree)  # noqa: E731
        # fixed validation plan (valid never shuffles); the loader's
        # CURRENT plan supplies epoch 1 IF it is still unconsumed
        # (_position 0: fresh initialize) — the same plan the graph loop
        # would consume — otherwise (snapshot resume: the restored plan
        # was already trained) a fresh shuffle is drawn, exactly as the
        # graph loop's next_minibatch would
        vidx, vmask = loader.plan_arrays(VALID)
        n_valid = int(vmask.sum())
        tidx, tmask = loader.plan_arrays(TEST)   # (None, None) if absent
        n_test = int(tmask.sum()) if tmask is not None else 0
        rng_stream = None
        if runner._has_stochastic:
            from veles_tpu import prng
            rng_stream = prng.get("dropout")
        # non-donating: the chunk-input state must survive the dispatch so
        # a completion inside the chunk can be replayed exactly (below)
        if trainer is not None:
            def chunk_fn(unused_state, unused_data, unused_labels, idx,
                         mask, vidx_, vmask_, rng, step0, tidx, tmask):
                return trainer.chunk_eval_pending(
                    idx, mask, vidx_, vmask_, rng=rng, step0=step0,
                    eval_first=True, tidx=tidx, tmask=tmask)
        else:
            inner_chunk = runner.epoch_chunk_eval_fn(
                self.chunk, eval_first=True, donate=False)

            def chunk_fn(state_, data_, labels_, idx, mask, vidx_,
                         vmask_, rng, step0, tidx_, tmask_):
                return inner_chunk(state_, data_, labels_, idx, mask,
                                   vidx_, vmask_, rng=rng, step0=step0,
                                   tidx=tidx_, tmask=tmask_)
        first_plan_fresh = loader._position == 0
        state = trainer.state if trainer is not None else runner.state
        snap = getattr(wf, "snapshotter", None)
        while not bool(dec.complete):
            plans = []
            for _ in range(self.chunk):
                if first_plan_fresh:
                    first_plan_fresh = False
                else:
                    loader._plan_epoch()
                plans.append(loader.plan_arrays(TRAIN))
            # the plan is consumed: snapshots must restore like the graph
            # loop's end-of-epoch state (next consumer replans)
            loader._position = len(loader._order)
            idx = numpy.stack([p[0] for p in plans])
            mask = numpy.stack([p[1] for p in plans])
            steps = idx.shape[-2]
            n_train = int(mask[0].sum())
            step0 = int(loader.epoch_number) * steps
            rng = rng_stream.key() if rng_stream is not None else None
            state_in = state
            state, train_stack, val_stack, test_stack = chunk_fn(
                state, data, labels, idx, mask, vidx, vmask, rng,
                step0, tidx, tmask)
            train_rows = fetch(train_stack)
            val_rows = fetch(val_stack)
            test_rows = (fetch(test_stack)
                         if test_stack is not None else None)
            done_row = None
            for row in range(self.chunk):
                loader.epoch_number = int(loader.epoch_number) + 1
                self._feed_decision(
                    {k: v[row] for k, v in train_rows.items()},
                    {k: v[row] for k, v in val_rows.items()},
                    ({k: v[row] for k, v in test_rows.items()}
                     if test_rows is not None else None),
                    (n_train, n_valid, n_test))
                fused = getattr(wf, "fused_step", None)
                if fused is not None:
                    fused.train_steps += steps
                if bool(dec.complete):
                    done_row = row
                    break
            if done_row is not None:
                # graph-mode parity: Decision.complete gate-skips the
                # commit of the stopping epoch's LAST minibatch — replay
                # rows 0..done_row from the kept input state with the
                # final epoch truncated to steps-1 minibatches
                if trainer is not None:
                    state = self._replay_spmd(trainer, idx, mask, rng,
                                              step0, done_row, steps)
                else:
                    state = self._replay_to_completion(
                        state_in, data, labels, idx, mask, rng, step0,
                        done_row, steps)
            # chunk boundary: state is addressable — commit, then the
            # snapshot gates fire (snapshot_state() syncs the runner
            # itself when it writes)
            if trainer is not None:
                trainer.state = state
                if done_row is None:
                    trainer.step_count = step0 + self.chunk * steps
                else:
                    # graph-mode parity for the COUNTER too: the graph
                    # loop dispatches (and counts in train_steps) the
                    # stopping epoch's last minibatch even though its
                    # commit is discarded; the replay trains steps-1, so
                    # set the counter to the full-epoch value — a
                    # resumed lr policy must start at the same step
                    trainer.step_count = step0 + (done_row + 1) * steps
            else:
                runner.state = state
            if snap is not None:
                loader.epoch_ended = True   # plain attr, like the loader
                snap.run()
            self._notify_reporters()
        if trainer is not None:
            trainer.state = state
            trainer.sync_to_runner()
        else:
            runner.state = state
            runner.sync_to_units()
        if snap is not None:
            snap.stop()
        wf._finished = True

    # ------------------------------------------------- streaming (windowed)
    def _run_streaming(self):
        """Windowed streaming epoch-scan: the dataset flows through HBM
        one device-resident window (``stream_window`` minibatches) at a
        time, each window one ``lax.scan`` dispatch, the next window
        staged concurrently by ``_WindowStager``.  Decision, snapshots
        and the completion-gate replay behave exactly like the chunked
        path at chunk=1; state commits at window granularity but is only
        made addressable (snapshots, unit sync) at epoch boundaries."""
        import jax
        wf = self.wf
        runner, loader, dec = self.runner, self.loader, self.decision
        if getattr(wf, "_sharded_trainer", None) is not None:
            raise ValueError(
                "--stream-window does not combine with --distributed "
                "yet: the windowed path is single-process (multi-host "
                "runs keep the HBM-resident chunk driver)")
        W = self.stream_window
        window_fn = runner.window_scan_fn()
        _, eval_fn = runner.epoch_fns()
        want_labels = not runner._is_mse

        def fetch(tree):
            return jax.tree.map(numpy.asarray, tree)

        stager = _WindowStager(loader, want_labels, self.stage_ahead,
                               name=loader.name + "_stager")
        stats = self.stream_stats = {
            "window_minibatches": W, "stage_ahead": self.stage_ahead,
            "epochs": 0, "windows": 0, "dispatches": 0,
            "train_samples": 0, "staging_stall_s": 0.0,
            "compute_s": 0.0, "samples_per_sec": 0.0,
            "staging_stall_fraction": 0.0,
        }
        wf._stream_stats = stats
        rng_stream = None
        if runner._has_stochastic:
            from veles_tpu import prng
            rng_stream = prng.get("dropout")
        try:
            # fixed validation (and optional test) windows: gathered and
            # uploaded ONCE, device-resident for the whole run — eval
            # sets are the small splits, and their plans never reshuffle
            vidx, vmask = loader.plan_arrays(VALID)
            n_valid = int(vmask.sum())
            vwin = stager.stage(vidx, vmask)
            tidx, tmask = loader.plan_arrays(TEST)
            twin = stager.stage(tidx, tmask) if tidx is not None else None
            n_test = int(tmask.sum()) if tmask is not None else 0

            def eval_row(win):
                x, y, lidx, m = win
                return fetch(eval_fn(runner_state, x, y, lidx, m))

            first_plan_fresh = loader._position == 0
            runner_state = runner.state
            snap = getattr(wf, "snapshotter", None)
            fused = getattr(wf, "fused_step", None)
            while not bool(dec.complete):
                if first_plan_fresh:
                    first_plan_fresh = False
                else:
                    loader._plan_epoch()
                idx, mask = loader.plan_arrays(TRAIN)
                loader._position = len(loader._order)   # plan consumed
                steps = idx.shape[0]
                n_train = int(mask.sum())
                step0 = int(loader.epoch_number) * steps
                epoch_rng = (rng_stream.key()
                             if rng_stream is not None else None)
                starts = list(range(0, steps, W))
                # set order parity with the graph loop and the chunked
                # driver (eval_first): test → validation BEFORE the
                # epoch's training, on the pre-epoch state
                test_row = eval_row(twin) if twin is not None else None
                val_row = eval_row(vwin)
                stats["dispatches"] += 1 + (twin is not None)
                for j in range(min(self.stage_ahead, len(starts))):
                    w0 = starts[j]
                    stager.submit(j, idx[w0:w0 + W], mask[w0:w0 + W])
                train_tot = None
                prev_state = last_win = last_rng = None
                for j, w0 in enumerate(starts):
                    win = stager.take(j)
                    nxt = j + self.stage_ahead
                    if nxt < len(starts):
                        n0 = starts[nxt]
                        stager.submit(nxt, idx[n0:n0 + W],
                                      mask[n0:n0 + W])
                    # per-window key: folding the epoch key by the
                    # window's global step offset keeps dropout draws
                    # distinct across windows (scan-path keys — the
                    # documented epoch-scan divergence)
                    wrng = (jax.random.fold_in(epoch_rng, step0 + w0)
                            if epoch_rng is not None else None)
                    if j == len(starts) - 1:
                        # kept alive for the completion-gate replay
                        prev_state, last_win, last_rng = \
                            runner_state, win, wrng
                    x, y, lidx, m = win
                    begin = time.perf_counter()
                    runner_state, totals = window_fn(
                        runner_state, x, y, lidx, m, wrng, step0 + w0)
                    totals = fetch(totals)   # host blocks; stager works
                    stats["compute_s"] += time.perf_counter() - begin
                    stats["windows"] += 1
                    stats["dispatches"] += 1
                    train_tot = (totals if train_tot is None else
                                 {k: train_tot[k] + v
                                  for k, v in totals.items()})
                loader.epoch_number = int(loader.epoch_number) + 1
                self._feed_decision(train_tot, val_row, test_row,
                                    (n_train, n_valid, n_test))
                if fused is not None:
                    # graph-mode parity for the counter: the discarded
                    # final-minibatch dispatch still counts
                    fused.train_steps += steps
                stats["epochs"] += 1
                stats["train_samples"] += n_train
                if bool(dec.complete):
                    # completion-gate artifact, window-sized: graph mode
                    # discards the stopping epoch's LAST minibatch
                    # commit, so replay the final window from its kept
                    # input state truncated to its first rows-1
                    # minibatches — one extra dispatch, once per run
                    x, y, lidx, m = last_win
                    rows = lidx.shape[0]
                    runner_state, _ = window_fn(
                        prev_state, x, y, lidx[:rows - 1], m[:rows - 1],
                        last_rng, step0 + starts[-1])
                    stats["dispatches"] += 1
                # epoch boundary: commit, then snapshot gates fire
                runner.state = runner_state
                busy = stats["compute_s"] + stager.stall_seconds
                stats["staging_stall_s"] = stager.stall_seconds
                stats["staging_stall_fraction"] = (
                    stager.stall_seconds / busy if busy > 0 else 0.0)
                stats["samples_per_sec"] = (
                    stats["train_samples"] / busy if busy > 0 else 0.0)
                if snap is not None:
                    loader.epoch_ended = True
                    snap.run()
                self._notify_reporters()
            runner.state = runner_state
            runner.sync_to_units()
            if snap is not None:
                snap.stop()
        finally:
            stager.shutdown()
        wf._finished = True

    def _replay_spmd(self, trainer, idx, mask, rng, step0, done_row,
                     steps):
        """SPMD form of :meth:`_replay_to_completion`: trainer.state is
        still the chunk input (chunk_eval_pending never commits), so the
        committing train_epochs/train_epoch calls replay rows 0..done_row
        with the final epoch truncated — same key folding as the chunk."""
        import jax
        if done_row > 0:
            trainer.train_epochs(idx[:done_row], mask[:done_row],
                                 rng=rng, step0=step0)
        off = step0 + done_row * steps
        erng = (jax.random.fold_in(rng, off) if rng is not None else None)
        trainer.train_epoch(idx[done_row][:steps - 1],
                            mask[done_row][:steps - 1],
                            rng=erng, step0=off)
        return trainer.state

    def _replay_to_completion(self, state, data, labels, idx, mask, rng,
                              step0, done_row, steps):
        """Exact final state: full epochs for chunk rows 0..done_row-1,
        then the stopping epoch WITHOUT its last minibatch (whose update
        graph mode discards).  One extra dispatch (plus one for the
        leading rows when done_row > 0), once per training run."""
        import jax
        runner = self.runner
        if done_row > 0:
            head = runner.epoch_chunk_fn(done_row)
            state, _ = head(state, data, labels, idx[:done_row],
                            mask[:done_row], rng=rng, step0=step0)
        off = step0 + done_row * steps
        erng = (jax.random.fold_in(rng, off) if rng is not None else None)
        train_epoch, _ = runner.epoch_fns()
        state, _ = train_epoch(state, data, labels,
                               idx[done_row][:steps - 1],
                               mask[done_row][:steps - 1],
                               rng=erng, step0=off)
        return state
